"""resilience/ subsystem tests: fault injection, crash-safe checkpoint I/O,
preemption + supervisor restart, elastic restore, goodput accounting.

The headline (ISSUE 2 acceptance) is ``test_e2e_preempt_supervisor_elastic``:
a real child process killed by an injected preemption at epoch K is
restarted by the ``Supervisor``, resumes from epoch K's checkpoint on a
DIFFERENT forced-host device count, and reaches final params allclose to an
uninterrupted run with the same seed; and a torn-write injection is caught
by the manifest check, with restore falling back to the previous good
checkpoint.
"""

import json
import os
import signal
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import serialization

from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.resilience import (
    EXIT_PREEMPTED,
    FaultPlan,
    FaultSpecError,
    GoodputMeter,
    Preempted,
    PreemptionHandler,
    Supervisor,
    aggregate_goodput,
    atomic_write_bytes,
    load_goodput_records,
    previous_path,
    read_manifest,
    verify_checkpoint,
    write_manifest,
)
from distributed_training_comparison_tpu.resilience.faults import tear_file
from distributed_training_comparison_tpu.train import (
    Trainer,
    configure_optimizers,
    create_train_state,
    find_valid_resume,
    find_version_dir,
    load_resume_state,
    make_epoch_runner,
    save_resume_state,
)
from distributed_training_comparison_tpu.train import checkpoint as ckpt_mod
from distributed_training_comparison_tpu.parallel import make_mesh, replicated_sharding

from test_train import HP, TinyNet

WORKER = Path(__file__).parent / "resil_worker.py"

WORKER_ARGS = [
    "--synthetic-data",
    "--limit-examples", "128",
    "--batch-size", "32",
    "--epoch", "3",
    "--save-last-min-secs", "0",
    "--no-progress",
    "--seed", "7",
]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(backend="ddp")


def _tiny_state(mesh):
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(TinyNet(dtype=jnp.float32), jax.random.key(0), tx)
    return jax.device_put(state, replicated_sharding(mesh))


# ------------------------------------------------------------- fault plans


def test_fault_plan_parse_and_triggers():
    plan = FaultPlan.parse(
        "preempt@epoch=2; torn_write@epoch=1, stall@epoch=0:secs=0.25"
    )
    assert plan.preempt_due(2) and not plan.preempt_due(1)
    assert plan.stall_secs(0) == 0.25 and plan.stall_secs(2) == 0.0
    assert plan.ckpt_hook(1) is not None and plan.ckpt_hook(0) is None
    assert FaultPlan.parse(None) is None and FaultPlan.parse("  ") is None


@pytest.mark.parametrize(
    "bad",
    ["explode@epoch=1", "preempt@", "preempt@epoch=x", "stall@epoch=1:mins=9"],
)
def test_fault_plan_rejects_malformed(bad):
    with pytest.raises(FaultSpecError):
        FaultPlan.parse(bad)


def test_fault_plan_prob_draws_are_seeded_and_deterministic():
    a = FaultPlan.parse("preempt@prob=0.5", seed=1)
    b = FaultPlan.parse("preempt@prob=0.5", seed=1)
    draws = [a.preempt_due(e) for e in range(32)]
    assert draws == [b.preempt_due(e) for e in range(32)]  # replayable
    assert any(draws) and not all(draws)  # actually Bernoulli
    c = FaultPlan.parse("preempt@prob=0.5", seed=2)
    assert draws != [c.preempt_due(e) for e in range(32)]  # seed matters


def test_bad_fault_plan_dies_at_the_cli():
    with pytest.raises(SystemExit):
        load_config("tpu", ["--fault-plan", "explode@epoch=1"])


# --------------------------------------------------- crash-safe ckpt I/O


def test_manifest_verify_detects_torn_write(tmp_path):
    path = tmp_path / "blob.ckpt"
    data = os.urandom(4096)
    atomic_write_bytes(path, data)
    write_manifest(path, data, meta={"step": 3})
    ok, reason = verify_checkpoint(path)
    assert ok, reason
    assert read_manifest(path)["step"] == 3

    tear_file(path)  # torn: payload halved, manifest untouched
    ok, reason = verify_checkpoint(path)
    assert not ok and "mismatch" in reason

    # same-size corruption is caught by the checksum (deep) pass
    atomic_write_bytes(path, data)
    write_manifest(path, data, meta={})
    path.write_bytes(os.urandom(len(data)))
    ok, reason = verify_checkpoint(path)
    assert not ok and "checksum" in reason


def test_legacy_checkpoint_without_manifest_is_accepted(tmp_path):
    path = tmp_path / "old.ckpt"
    path.write_bytes(b"pre-manifest era")
    ok, reason = verify_checkpoint(path)
    assert ok and "legacy" in reason


def test_corrupt_manifest_is_rejected_not_legacy(tmp_path):
    """A manifest that exists but doesn't parse is corruption (the same
    event that may have torn the payload) — it must NOT downgrade the
    checkpoint to legacy-accepted, and rotation must not evict a good
    prev copy for it."""
    from distributed_training_comparison_tpu.resilience import (
        manifest_path,
        rotate_previous,
    )

    path = tmp_path / "blob.ckpt"
    data = os.urandom(1024)
    atomic_write_bytes(path, data)
    write_manifest(path, data, meta={})
    manifest_path(path).write_bytes(b"{torn json")
    ok, reason = verify_checkpoint(path)
    assert not ok and "unreadable" in reason
    assert rotate_previous(path) is None  # refuses to rotate unverifiable bytes


def test_resume_rotation_and_fallback(tmp_path, mesh):
    """A torn newest last.ckpt must cost one save interval, not the run:
    find_valid_resume falls back to the rotated previous good checkpoint."""
    state = _tiny_state(mesh)
    vdir = find_version_dir(tmp_path)
    save_resume_state(vdir, state, epoch=0, best_acc=10.0)
    save_resume_state(vdir, state, epoch=1, best_acc=11.0)
    last = vdir / "last.ckpt"
    prev = previous_path(last)
    assert prev.exists() and read_manifest(prev)["epoch"] == 0
    assert read_manifest(last)["epoch"] == 1
    assert find_valid_resume(tmp_path) == last

    tear_file(last)
    assert find_valid_resume(tmp_path) == prev
    restored, next_epoch, best = load_resume_state(prev, _tiny_state(mesh))
    assert next_epoch == 1 and best == 10.0

    tear_file(prev)  # both torn → no resume, fresh start
    assert find_valid_resume(tmp_path) is None


# --------------------------------------------------------- version dirs


def test_find_version_dir_claim_is_race_safe(tmp_path):
    """32 concurrent claims must produce 32 distinct dirs — the mkdir IS
    the claim (the old scan-then-mkdir(exist_ok=True) let two processes
    share a slot)."""
    with ThreadPoolExecutor(8) as ex:
        dirs = list(ex.map(lambda _: find_version_dir(tmp_path), range(32)))
    names = {d.name for d in dirs}
    assert len(names) == 32
    assert all(d.exists() for d in dirs)


def test_agreed_version_dir_rank0_picks_others_follow(tmp_path, monkeypatch):
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)

    # rank 1: follows the broadcast pick, creates nothing
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    monkeypatch.setattr(
        multihost_utils, "broadcast_one_to_all", lambda x: np.asarray(3)
    )
    d = ckpt_mod.agreed_version_dir(tmp_path)
    assert d.name == "version-3" and not d.exists()

    # rank 0: claims race-safely and broadcasts its claim
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    sent = {}

    def record_broadcast(x):
        sent["value"] = int(np.asarray(x))
        return np.asarray(x)

    monkeypatch.setattr(multihost_utils, "broadcast_one_to_all", record_broadcast)
    d0 = ckpt_mod.agreed_version_dir(tmp_path)
    assert d0.exists() and sent["value"] == int(d0.name.split("-")[-1])


# ------------------------------------------------------------- preemption


def test_preemption_handler_latches_sigterm_and_restores():
    handler = PreemptionHandler().install()
    try:
        assert not handler.triggered
        os.kill(os.getpid(), signal.SIGTERM)  # latched, not fatal
        assert handler.triggered
    finally:
        handler.restore()
    assert signal.getsignal(signal.SIGTERM) is not handler._on_signal


def test_trainer_preempt_fault_drains_and_raises(tmp_path):
    hp = load_config(
        "tpu",
        argv=WORKER_ARGS
        + ["--ckpt-path", str(tmp_path), "--fault-plan", "preempt@epoch=1"],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    with pytest.raises(Preempted) as exc:
        trainer.fit()
    trainer.close()
    assert exc.value.epoch == 1
    vdir = tmp_path / "version-0"
    manifest = read_manifest(vdir / "last.ckpt")
    assert manifest["epoch"] == 1  # epoch K's checkpoint landed before exit
    records = load_goodput_records(vdir / "goodput.jsonl")
    assert len(records) == 1 and records[0]["preempted"] is True
    assert records[0]["step_s"] > 0


def test_trainer_ckpt_fail_fault_surfaces_via_writer(tmp_path):
    """An injected checkpoint-write failure must surface as a loud error
    through AsyncCheckpointer.wait(), never a silent no-checkpoint run."""
    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "128",
            "--batch-size", "32", "--epoch", "1",
            "--save-last-min-secs", "0", "--no-progress",
            "--ckpt-path", str(tmp_path),
            "--fault-plan", "ckpt_fail@epoch=0",
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    with pytest.raises(RuntimeError, match="injected checkpoint write failure"):
        trainer.fit()
    trainer.close()


def test_trainer_torn_write_fault_then_auto_resume_falls_back(tmp_path):
    """Acceptance: a torn-write injection is detected by the manifest check
    and restore falls back to the previous good checkpoint."""
    argv = WORKER_ARGS + ["--ckpt-path", str(tmp_path)]
    # The stalls (exercising the stall fault path) double as writer-drain
    # windows: without them three sub-second epochs can queue all three
    # "last" saves before the writer thread runs once, and same-key
    # coalescing would then (legitimately) write only the final, torn one —
    # leaving no prev- fallback to test.
    hp = load_config(
        "tpu",
        argv=argv + [
            "--fault-plan",
            "stall@epoch=0:secs=0.2;stall@epoch=1:secs=0.2;"
            "torn_write@epoch=2;preempt@epoch=2",
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    with pytest.raises(Preempted):
        trainer.fit()  # epoch 2's last.ckpt lands, then is torn
    trainer.close()
    vdir = tmp_path / "version-0"
    ok, reason = verify_checkpoint(vdir / "last.ckpt")
    assert not ok and "mismatch" in reason
    records = load_goodput_records(vdir / "goodput.jsonl")
    assert records[0]["stall_s"] >= 0.4  # both injected stalls accounted

    resumed = Trainer(
        load_config("tpu", argv=argv + ["--auto-resume"]),
        model=TinyNet(num_classes=100),
    )
    # fell back to the previous good checkpoint.  Its epoch is 0 or 1
    # depending on writer-thread coalescing (a queued epoch-1 save may be
    # superseded by epoch 2's before it starts) — what must hold is that
    # resume continues from exactly the epoch the fallback manifest records.
    assert resumed.hparams.resume.endswith("prev-last.ckpt")
    prev_epoch = read_manifest(vdir / "prev-last.ckpt")["epoch"]
    assert resumed.start_epoch == prev_epoch + 1 <= 3
    version = resumed.fit()
    resumed.close()
    assert version == 0  # continued in place, no new version dir
    assert read_manifest(vdir / "last.ckpt")["epoch"] == 2  # run completed


# ------------------------------------------------------------- supervisor


def test_supervisor_crash_backoff_and_budget():
    rcs = iter([1, 1, 0])
    sleeps = []
    sup = Supervisor(
        ["true"],
        max_restarts=3,
        backoff_base=0.5,
        runner=lambda cmd, env: next(rcs),
        sleep=sleeps.append,
        log=lambda msg: None,
    )
    summary = sup.run()
    assert summary["final_rc"] == 0 and summary["restarts"] == 2
    assert sleeps == [0.5, 1.0]  # exponential
    assert summary["downtime_s"] == 1.5

    sup = Supervisor(
        ["true"],
        max_restarts=2,
        backoff_base=0.1,
        runner=lambda cmd, env: 9,
        sleep=lambda s: None,
        log=lambda msg: None,
    )
    summary = sup.run()
    assert summary["final_rc"] == 9
    assert len(summary["attempts"]) == 3  # initial + 2 budgeted restarts


def test_supervisor_counts_budget_exhausting_preemption():
    """A final preempted attempt that exhausts the budget must still be
    counted — GOODPUT.json's preemptions field must agree with the
    attempt list."""
    sup = Supervisor(
        ["true"],
        max_restarts=1,
        runner=lambda cmd, env: EXIT_PREEMPTED,
        sleep=lambda s: None,
        log=lambda msg: None,
    )
    summary = sup.run()
    assert summary["final_rc"] == EXIT_PREEMPTED
    assert len(summary["attempts"]) == 2
    assert summary["preemptions"] == 2  # both attempts, incl. the last one


def test_supervisor_preemption_restarts_without_backoff():
    rcs = iter([EXIT_PREEMPTED, EXIT_PREEMPTED, 0])
    sleeps = []
    seen_cmds = []
    sup = Supervisor(
        lambda attempt: ["attempt", str(attempt)],
        max_restarts=5,
        runner=lambda cmd, env: (seen_cmds.append(list(cmd)), next(rcs))[1],
        sleep=sleeps.append,
        log=lambda msg: None,
    )
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert summary["preemptions"] == 2 and sleeps == []  # no backoff
    assert seen_cmds == [["attempt", "0"], ["attempt", "1"], ["attempt", "2"]]
    assert [a["preempted"] for a in summary["attempts"]] == [True, True, False]


def test_strip_resume_flag_both_forms():
    """Restart attempts must drop an explicit --resume (attempt 0's
    original-checkpoint pointer) so --auto-resume can pick up the progress
    the previous attempt actually made."""
    from distributed_training_comparison_tpu.resilience.supervisor import (
        strip_resume_flag,
    )

    args = ["--epoch", "5", "--resume", "run/last.ckpt", "--auto-resume"]
    assert strip_resume_flag(args) == ["--epoch", "5", "--auto-resume"]
    args = ["--resume=run/last.ckpt", "--epoch", "5"]
    assert strip_resume_flag(args) == ["--epoch", "5"]
    assert strip_resume_flag(["--epoch", "5"]) == ["--epoch", "5"]


# ---------------------------------------------------------------- goodput


def test_goodput_meter_and_aggregate():
    meter = GoodputMeter()
    meter.add("step", 6.0)
    meter.add("ckpt", 1.0)
    with meter.phase("eval"):
        pass
    summary = meter.summary()
    assert summary["step_s"] == 6.0 and summary["ckpt_s"] == 1.0
    assert summary["wall_s"] >= 0

    report = aggregate_goodput(
        [
            {"step_s": 6.0, "ckpt_s": 1.0, "wall_s": 8.0},
            {"step_s": 3.0, "ckpt_s": 0.5, "wall_s": 4.0},
        ],
        downtime_s=3.0,
        restarts=1,
        preemptions=1,
    )
    assert report["productive_s"] == 9.0
    assert report["total_wall_s"] == 15.0  # 8 + 4 + 3 downtime
    assert report["goodput_frac"] == pytest.approx(9.0 / 15.0, abs=1e-4)
    assert report["restarts"] == 1 and report["attempts"] == 2


def test_collect_goodput_records_spans_version_dirs(tmp_path):
    """An attempt that died before its first save leaves its record in one
    version dir while the relaunch progresses in the next — aggregation
    must see both, and `since` must exclude older runs' records."""
    from distributed_training_comparison_tpu.resilience.goodput import (
        collect_goodput_records,
    )

    for n, (step_s, written_at) in enumerate([(1.0, 50.0), (2.0, 100.0)]):
        d = tmp_path / f"version-{n}"
        d.mkdir()
        (d / "goodput.jsonl").write_text(
            json.dumps({"step_s": step_s, "written_at": written_at}) + "\n"
        )
    assert [r["step_s"] for r in collect_goodput_records(tmp_path)] == [1.0, 2.0]
    assert [
        r["step_s"] for r in collect_goodput_records(tmp_path, since=75.0)
    ] == [2.0]


def test_goodput_records_survive_torn_trailing_line(tmp_path):
    path = tmp_path / "goodput.jsonl"
    path.write_text('{"step_s": 1.0}\n{"step_s": 2.0}\n{"torn...')
    assert [r["step_s"] for r in load_goodput_records(path)] == [1.0, 2.0]


# ------------------------------------------------------------ elastic


def test_elastic_restore_across_device_counts_in_process(tmp_path):
    """Save on an 8-device mesh, restore onto a 4-device mesh: step/epoch
    accounting intact, and the next epoch's trajectory matches the
    8-device continuation (reduction-order tolerance only)."""
    x, y = (
        jnp.asarray(np.random.default_rng(0).normal(size=(64, 32, 32, 3)).astype(np.float32)),
        jnp.asarray(np.random.default_rng(1).integers(0, 10, size=(64,)).astype(np.int32)),
    )
    mesh8 = make_mesh(backend="ddp")
    runner8 = make_epoch_runner(mesh8, batch_size=32)
    state = _tiny_state(mesh8)
    key = jax.random.key(3)
    state, _ = runner8(state, x, y, key, jnp.asarray(0))
    save_resume_state(find_version_dir(tmp_path), state, epoch=0, best_acc=1.0)

    mesh4 = make_mesh(4, backend="ddp")
    restored, next_epoch, _ = load_resume_state(
        tmp_path / "version-0" / "last.ckpt", _tiny_state(mesh4)
    )
    restored = jax.device_put(restored, replicated_sharding(mesh4))
    assert next_epoch == 1 and int(restored.step) == 2

    state8, s8 = runner8(state, x, y, key, jnp.asarray(1))
    runner4 = make_epoch_runner(mesh4, batch_size=32)
    state4, s4 = runner4(restored, x, y, key, jnp.asarray(1))
    np.testing.assert_allclose(
        np.asarray(s4["loss"]), np.asarray(s8["loss"]), rtol=1e-5, atol=1e-6
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        jax.device_get(state4.params),
        jax.device_get(state8.params),
    )


# ----------------------------------------------------------- e2e headline


@pytest.mark.elastic
def test_e2e_preempt_supervisor_elastic(tmp_path, forced_device_env):
    """ISSUE 2 acceptance: child preempted at epoch 1 (8 devices) →
    supervisor relaunches with --auto-resume on 4 devices → resumes from
    epoch 1's checkpoint → final params allclose to an uninterrupted
    same-seed run."""
    ckpt_root = tmp_path / "faulted"
    args = WORKER_ARGS + [
        "--ckpt-path", str(ckpt_root),
        "--auto-resume",
        "--fault-plan", "preempt@epoch=1",
    ]

    def runner(cmd, env):
        proc = subprocess.run(
            cmd, env=env, cwd=WORKER.parent.parent,
            capture_output=True, text=True, timeout=300,
        )
        assert "Traceback" not in (proc.stderr or ""), proc.stderr[-3000:]
        return proc.returncode

    sup = Supervisor(
        [sys.executable, str(WORKER)] + args,
        env=lambda attempt: forced_device_env(8 if attempt == 0 else 4),
        max_restarts=3,
        backoff_base=0.05,
        runner=runner,
        log=lambda msg: None,
    )
    summary = sup.run()
    assert summary["final_rc"] == 0, summary
    assert summary["restarts"] == 1 and summary["preemptions"] == 1
    assert summary["attempts"][0]["returncode"] == EXIT_PREEMPTED

    vdir = ckpt_root / "version-0"
    records = load_goodput_records(vdir / "goodput.jsonl")
    assert len(records) == 2
    assert records[0]["preempted"] and records[0]["topology"]["devices"] == 8
    assert not records[1]["preempted"] and records[1]["topology"]["devices"] == 4
    assert records[1]["start_epoch"] == 2  # resumed from epoch 1's checkpoint
    report = aggregate_goodput(records, restarts=summary["restarts"])
    assert report["productive_s"] > 0

    # uninterrupted run, same seed, on this process's 8-device mesh
    clean_root = tmp_path / "clean"
    hp = load_config("tpu", argv=WORKER_ARGS + ["--ckpt-path", str(clean_root)])
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    trainer.fit()
    trainer.close()

    def final_params(root):
        raw = serialization.msgpack_restore(
            (root / "version-0" / "last.ckpt").read_bytes()
        )
        assert raw["epoch"] == 2  # all 3 epochs completed
        return raw["state"]["params"]

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        final_params(ckpt_root),
        final_params(clean_root),
    )


# --------------------------------------------------------------- entry


def test_entry_maps_preempted_to_exit_code(tmp_path, monkeypatch):
    from distributed_training_comparison_tpu import entry

    class StubTrainer:
        version = 0

        def __init__(self, hparams):
            pass

        def fit(self):
            raise Preempted(epoch=4, step=40)

        def close(self):
            pass

    monkeypatch.setattr(entry, "Trainer", StubTrainer)
    results = entry.run(
        "single",
        argv=["--synthetic-data", "--ckpt-path", str(tmp_path)],
    )
    assert results["preempted"] is True and results["epoch"] == 4
    assert results["exit_code"] == EXIT_PREEMPTED
