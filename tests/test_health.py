"""health/ subsystem tests: compiled numerics guards, spike/desync
detection, the watchdog's automatic rollback, and the fault-plan kinds that
drive them — plus the satellite paths (mid-epoch host-mode preemption,
supervisor progress probe, async-writer utilization gauge).

The headline (ISSUE 3 acceptance) is
``test_e2e_nan_and_spike_rollback_matches_clean``: a seeded
``nan_grad@epoch=1;loss_spike@epoch=2`` plan mid-run → the compiled guard
skips the non-finite steps, the median/MAD window flags the spikes, the
watchdog rolls back to the last good checkpoint twice and replays clean →
the final params and eval metrics match (allclose) an uninterrupted run
with the same seed, with every skip/rollback on record in health.jsonl +
HEALTH.json and the wasted epochs charged to goodput's ``rollback`` phase.
"""

import json

import flax.linen as lnn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import serialization

from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.data import synthetic_dataset
from distributed_training_comparison_tpu.health import (
    SpikeDetector,
    Watchdog,
    check_desync,
    global_norm,
    load_health_events,
    param_fingerprint,
)
from distributed_training_comparison_tpu.health.watchdog import HealthConfig
from distributed_training_comparison_tpu.parallel import make_mesh, replicated_sharding
from distributed_training_comparison_tpu.resilience import (
    EXIT_PREEMPTED,
    FaultPlan,
    FaultSpecError,
    GoodputMeter,
    Preempted,
    Supervisor,
    aggregate_goodput,
    load_goodput_records,
    read_manifest,
)
from distributed_training_comparison_tpu.train import (
    Trainer,
    configure_optimizers,
    create_train_state,
    make_epoch_runner,
    make_train_step,
)

from test_train import HP, TinyNet

BASE_ARGS = [
    "--synthetic-data",
    "--limit-examples", "640",   # 576 train examples -> 18 steps/epoch @32
    "--batch-size", "32",
    "--epoch", "4",
    "--save-last-min-secs", "0",
    "--no-progress",
    "--seed", "7",
    "--eval-step", "1000",
]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(backend="ddp")


@pytest.fixture(scope="module")
def tiny_data():
    x, y = synthetic_dataset(256, num_classes=10, seed=0)
    return jnp.asarray(x), jnp.asarray(y)


def _fresh_state(mesh):
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(TinyNet(dtype=jnp.float32), jax.random.key(0), tx)
    return jax.device_put(state, replicated_sharding(mesh))


def _params_equal(a, b):
    flat_a = jax.tree_util.tree_leaves(jax.device_get(a))
    flat_b = jax.tree_util.tree_leaves(jax.device_get(b))
    return all(np.array_equal(x, y) for x, y in zip(flat_a, flat_b))


# ------------------------------------------------------------ fault plans


def test_fault_plan_parses_health_kinds():
    plan = FaultPlan.parse(
        "nan_grad@epoch=1; loss_spike@epoch=2:steps=4:scale=8, "
        "bad_batch@epoch=0:step=5; desync@epoch=3"
    )
    assert plan.has_step_faults()
    scale, start, stop = plan.step_fault(2, steps_per_epoch=20)
    assert (scale, start, stop) == (8.0, 10, 14)
    assert plan.step_fault(2, 20) == (1.0, 0, 0)  # consumed: replay is clean
    scale, start, stop = plan.step_fault(1, 20)
    assert np.isnan(scale) and (start, stop) == (0, 3)  # nan_grad defaults
    assert plan.step_fault(0, 20) == (float("inf"), 5, 6)  # bad_batch @step
    assert plan.desync_due(3) and not plan.desync_due(3)  # one-shot
    assert not plan.desync_due(1)


def test_fault_plan_rejects_malformed_health_args():
    with pytest.raises(FaultSpecError):
        FaultPlan.parse("nan_grad@epoch=1:scale=x")
    with pytest.raises(FaultSpecError):
        FaultPlan.parse("loss_spike@steps=3")  # no trigger
    with pytest.raises(SystemExit):
        load_config("tpu", ["--fault-plan", "nan_grad@epoch=1:mins=2"])


def test_fault_plan_mid_epoch_preempt_semantics():
    plan = FaultPlan.parse("preempt@epoch=1:step=4")
    # boundary: device mode fires it at the epoch's end, host mode must not
    assert plan.preempt_due(1, include_step_events=True)
    assert not plan.preempt_due(1, include_step_events=False)
    # chunk poll: fires once >= step 4 steps are done...
    assert not plan.preempt_step_due(1, done=2)
    assert plan.preempt_step_due(1, done=4)
    # ...but never for an attempt that RESUMED at-or-past it (one-shot)
    assert not plan.preempt_step_due(1, done=14, start_offset=4)
    assert not plan.preempt_step_due(0, done=14)  # wrong epoch
    # an out-of-range step clamps to the epoch's step count (fires at the
    # boundary instead of silently never)
    plan = FaultPlan.parse("preempt@epoch=1:step=99")
    assert not plan.preempt_step_due(1, done=12, cap=14)
    assert plan.preempt_step_due(1, done=14, cap=14)
    # step=0 means "as soon as possible", not "never" (0 < 0 would drop it)
    plan = FaultPlan.parse("preempt@epoch=1:step=0")
    assert plan.preempt_step_due(1, done=2)
    assert not plan.preempt_step_due(1, done=2, start_offset=1)


# ------------------------------------------------- compiled numerics guards


def test_global_norm_flags_nonfinite():
    tree = {"a": jnp.ones((4,)), "b": jnp.full((2,), 2.0)}
    assert float(global_norm(tree)) == pytest.approx(np.sqrt(4 + 8))
    tree["b"] = jnp.array([1.0, np.nan])
    assert not np.isfinite(float(global_norm(tree)))
    tree["b"] = jnp.array([1.0, np.inf])
    assert not np.isfinite(float(global_norm(tree)))
    assert float(global_norm({})) == 0.0


def test_guarded_epoch_skips_nonfinite_and_freezes_state(mesh, tiny_data):
    """NaN-poisoned steps must apply NOTHING (params, BN stats, opt state,
    step counter all frozen) and report per-step skip flags that ride the
    stacked metrics fetch."""
    x, y = tiny_data
    # donate=False: this test deliberately re-reads the INPUT state after
    # the call to prove the guard froze it (the trainer's hot path donates)
    runner = make_epoch_runner(
        mesh, batch_size=64, fault_injection=True, donate=False
    )
    state = _fresh_state(mesh)
    key = jax.random.key(3)

    # every step poisoned: the epoch is a no-op on the state
    out_state, stacked = runner(
        state, x, y, key, jnp.asarray(0), (float("nan"), 0, 4)
    )
    assert np.all(np.asarray(stacked["skipped"]) == 1.0)
    assert not np.isfinite(np.asarray(stacked["grad_norm"])).any()
    assert int(out_state.step) == int(state.step)
    assert _params_equal(out_state.params, state.params)
    assert _params_equal(out_state.batch_stats, state.batch_stats)

    # partial window: only the poisoned steps skip, the rest train
    out_state, stacked = runner(
        state, x, y, key, jnp.asarray(0), (float("nan"), 1, 3)
    )
    np.testing.assert_array_equal(
        np.asarray(stacked["skipped"]), [0.0, 1.0, 1.0, 0.0]
    )
    assert int(out_state.step) == int(state.step) + 2
    assert not _params_equal(out_state.params, state.params)


def test_fault_scale_injection_is_windowed_and_benign_at_one(mesh, tiny_data):
    """scale=1 must reproduce the unfaulted trajectory exactly, and a spike
    window must scale exactly the targeted step's loss metric."""
    x, y = tiny_data
    state = _fresh_state(mesh)
    key = jax.random.key(3)
    # donate=False: one state feeds three runner calls side by side
    plain = make_epoch_runner(mesh, batch_size=64, donate=False)
    faulted = make_epoch_runner(
        mesh, batch_size=64, fault_injection=True, donate=False
    )
    _, s_plain = plain(state, x, y, key, jnp.asarray(0))
    _, s_benign = faulted(state, x, y, key, jnp.asarray(0), (1.0, 0, 0))
    np.testing.assert_allclose(
        np.asarray(s_benign["loss"]), np.asarray(s_plain["loss"]),
        rtol=1e-6, atol=0,
    )
    assert np.all(np.asarray(s_benign["skipped"]) == 0.0)

    _, s_spike = faulted(state, x, y, key, jnp.asarray(0), (64.0, 2, 3))
    losses = np.asarray(s_spike["loss"])
    base = np.asarray(s_plain["loss"])
    np.testing.assert_allclose(losses[:2], base[:2], rtol=1e-6)
    assert losses[2] == pytest.approx(64.0 * base[2], rel=1e-5)
    assert np.all(np.asarray(s_spike["skipped"]) == 0.0)  # finite: applied


def test_moe_metrics_nan_does_not_poison_skip_decision(mesh):
    """Sown dispatch metrics may be NaN (a collapsed router under bf16, a
    non-finite logit) without vetoing a healthy update; a NaN AUX LOSS must
    veto it (it sums into the objective)."""

    class NaNMetricsNet(lnn.Module):
        @lnn.compact
        def __call__(self, x, train=False):
            feats = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
            self.sow("moe_metrics", "expert_load", jnp.full((1, 4), jnp.nan))
            return lnn.Dense(10)(feats)

    class NaNAuxLossNet(lnn.Module):
        @lnn.compact
        def __call__(self, x, train=False):
            feats = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
            self.sow("losses", "aux", jnp.asarray(jnp.nan, jnp.float32))
            return lnn.Dense(10)(feats)

    x, y = synthetic_dataset(64, num_classes=10, seed=1)
    x, y = jnp.asarray(x), jnp.asarray(y)
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    step = make_train_step(mesh)

    state = create_train_state(NaNMetricsNet(), jax.random.key(0), tx)
    state = jax.device_put(state, replicated_sharding(mesh))
    new_state, metrics = step(state, x, y, jax.random.key(1))
    assert float(metrics["skipped"]) == 0.0  # NaN diagnostics: still applied
    assert np.isnan(float(metrics["moe_load_max"]))
    assert int(new_state.step) == 1

    state = create_train_state(NaNAuxLossNet(), jax.random.key(0), tx)
    state = jax.device_put(state, replicated_sharding(mesh))
    new_state, metrics = step(state, x, y, jax.random.key(1))
    assert float(metrics["skipped"]) == 1.0  # NaN objective: guarded out
    assert int(new_state.step) == 0
    assert _params_equal(new_state.params, state.params)


# ----------------------------------------------------------- spike detector


def test_spike_detector_flags_outliers_after_warmup():
    det = SpikeDetector(window=32, threshold_mads=8.0, min_baseline=16)
    rng = np.random.default_rng(0)
    base = 2.0 + 0.05 * rng.standard_normal(8)
    # warmup: even a huge value must not flag before the baseline exists
    flags = det.observe(np.append(base, 50.0), np.zeros(9))
    assert not flags.any()
    det.observe(2.0 + 0.05 * rng.standard_normal(16), np.zeros(16))
    losses = 2.0 + 0.05 * rng.standard_normal(10)
    losses[3] = 50.0
    flags = det.observe(losses, np.zeros(10))
    assert flags[3] and flags.sum() == 1
    # the outlier never entered the window: an identical spike still flags
    assert det.observe(np.asarray([50.0]), np.zeros(1))[0]
    # skipped (non-finite) steps are the guard's business, never spikes
    assert not det.observe(np.asarray([np.nan]), np.ones(1))[0]


def test_watchdog_rollback_needs_k_consecutive_bad_steps():
    losses = np.asarray([2.0, 2.0, np.nan, np.nan, np.nan, 2.0])
    skipped = np.asarray([0, 0, 1, 1, 1, 0], np.float32)
    wd = Watchdog(HealthConfig(bad_steps=3, min_baseline=64))
    verdict = wd.observe_epoch(0, losses, skipped)
    assert verdict.rollback and verdict.skipped == 3 and verdict.max_bad_run == 3
    assert verdict.nonfinite
    wd = Watchdog(HealthConfig(bad_steps=4, min_baseline=64))
    verdict = wd.observe_epoch(0, losses, skipped)
    assert not verdict.rollback and wd.skipped_steps == 3
    assert wd.events and wd.events[0]["kind"] == "skip"


# ------------------------------------------------------------------- desync


def test_check_desync_single_process_and_injection(mesh):
    state = _fresh_state(mesh)
    fp = float(jax.jit(param_fingerprint)(state.params))
    assert np.isfinite(fp) and fp > 0
    report = check_desync(fp)
    assert not report["mismatch"] and report["spread"] == 0.0
    report = check_desync(fp, inject=True)
    assert report["mismatch"] and report["injected"]
    assert report["spread"] >= 1.0
    # the injected drift must survive float32 rounding at LARGE fingerprints
    # (a flat +1.0 is absorbed past 2^24)
    report = check_desync(3.4e7, inject=True)
    assert report["mismatch"] and report["spread"] > 0


def test_param_fingerprint_detects_leaf_swaps():
    a = {"x": jnp.full((2,), 1.0), "y": jnp.full((2,), 3.0)}
    b = {"x": jnp.full((2,), 3.0), "y": jnp.full((2,), 1.0)}
    assert float(param_fingerprint(a)) != float(param_fingerprint(b))


# ------------------------------------------------- trainer e2e (acceptance)


def _fit(tmp_path, extra=(), model=None):
    hp = load_config("tpu", argv=BASE_ARGS + ["--ckpt-path", str(tmp_path), *extra])
    trainer = Trainer(hp, model=model or TinyNet(num_classes=100))
    trainer.fit()
    val = trainer.validate(0)
    trainer.close()
    return trainer, val


def _last_ckpt_params(root):
    raw = serialization.msgpack_restore(
        (root / "version-0" / "last.ckpt").read_bytes()
    )
    return raw["epoch"], raw["state"]["params"]


@pytest.mark.health
def test_e2e_nan_and_spike_rollback_matches_clean(tmp_path):
    """ISSUE 3 acceptance: nan_grad + loss_spike injected mid-run → the
    guard skips, the watchdog rolls back twice and replays clean → final
    params and eval metrics allclose an uninterrupted same-seed run, with
    the damage on record (health.jsonl, HEALTH.json, goodput rollback)."""
    health_json = tmp_path / "HEALTH.json"
    clean_t, clean_val = _fit(tmp_path / "clean")
    faulted_t, faulted_val = _fit(
        tmp_path / "faulted",
        extra=[
            "--fault-plan", "nan_grad@epoch=1;loss_spike@epoch=2",
            "--health-json", str(health_json),
        ],
    )
    wd = faulted_t.watchdog
    assert wd.skipped_steps == 3       # nan_grad's 3 poisoned steps
    assert wd.spike_steps >= 3         # the spiked window (damage may extend it)
    assert wd.rollbacks == 2           # one per faulted epoch
    assert wd.desyncs == 0

    # converge-anyway: the replayed trajectory IS the clean trajectory
    epoch, faulted_params = _last_ckpt_params(tmp_path / "faulted")
    clean_epoch, clean_params = _last_ckpt_params(tmp_path / "clean")
    assert epoch == clean_epoch == 3
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        faulted_params, clean_params,
    )
    assert faulted_val["val_acc"] == pytest.approx(clean_val["val_acc"], abs=0.5)
    assert faulted_val["val_loss"] == pytest.approx(clean_val["val_loss"], rel=1e-3)

    # the paper trail: events + HEALTH.json + rollback-phase goodput
    events = load_health_events(tmp_path / "faulted" / "version-0" / "health.jsonl")
    assert sum(e["kind"] == "rollback" for e in events) == 2
    report = json.loads(health_json.read_bytes())
    assert report["rollbacks"] == 2 and report["skipped_steps"] == 3
    records = load_goodput_records(
        tmp_path / "faulted" / "version-0" / "goodput.jsonl"
    )
    assert records[0]["rollback_s"] > 0
    assert records[0]["health"]["rollbacks"] == 2
    assert 0.0 <= records[0]["ckpt_writer"]["busy_frac"] <= 1.0


@pytest.mark.health
def test_e2e_desync_detect_rollback_converges(tmp_path):
    """An injected replica desync after a CLEAN epoch rolls back and replays
    — since no damage was ever applied, the final state matches the clean
    run exactly (allclose)."""
    clean_t, _ = _fit(tmp_path / "clean")
    faulted_t, _ = _fit(
        tmp_path / "faulted", extra=["--fault-plan", "desync@epoch=1"]
    )
    assert faulted_t.watchdog.desyncs == 1
    assert faulted_t.watchdog.rollbacks == 1
    _, faulted_params = _last_ckpt_params(tmp_path / "faulted")
    _, clean_params = _last_ckpt_params(tmp_path / "clean")
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        faulted_params, clean_params,
    )


@pytest.mark.health
def test_no_health_aborts_on_skipped_steps(tmp_path):
    """--no-health keeps the pre-watchdog contract: the compiled guard
    still holds the state, but non-finite grads (even under a finite loss)
    abort loudly — there is no recovery policy to absorb them."""
    hp = load_config(
        "tpu",
        argv=BASE_ARGS + [
            "--ckpt-path", str(tmp_path), "--no-health",
            "--fault-plan", "nan_grad@epoch=0:steps=1",
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    assert trainer.watchdog is None
    with pytest.raises(FloatingPointError, match="non-finite train loss"):
        trainer.fit()
    trainer.close()


@pytest.mark.health
def test_rollback_falls_back_to_resume_source_before_first_save(tmp_path):
    """An explicit --resume trains in a FRESH version dir: a bad epoch
    before its first save must roll back to the (read-only) source
    checkpoint, not give up — and still converge to the clean trajectory."""
    _fit(tmp_path / "src")  # donor run: version-0 with last.ckpt at epoch 3
    src_last = tmp_path / "src" / "version-0" / "last.ckpt"
    argv = BASE_ARGS[:]
    argv[argv.index("--epoch") + 1] = "6"
    hp = load_config(
        "tpu",
        argv=argv + [
            "--ckpt-path", str(tmp_path / "dst"),
            "--resume", str(src_last),
            "--fault-plan", "nan_grad@epoch=4",
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    assert trainer.start_epoch == 4
    trainer.fit()
    trainer.close()
    assert trainer.watchdog.rollbacks == 1  # via the source fallback
    epoch, _ = _last_ckpt_params(tmp_path / "dst")
    assert epoch == 5  # run completed in its own fresh dir
    assert src_last.exists()  # source untouched


@pytest.mark.health
def test_e2e_single_bad_batch_absorbed_without_rollback(tmp_path):
    """One corrupt batch (Inf loss) is the cheap case: the compiled guard
    skips its update, the run keeps training — no rollback, one skip on
    record."""
    trainer, _ = _fit(
        tmp_path, extra=["--fault-plan", "bad_batch@epoch=1"]
    )
    assert trainer.watchdog.skipped_steps == 1
    assert trainer.watchdog.rollbacks == 0
    epoch, _ = _last_ckpt_params(tmp_path)
    assert epoch == 3  # completed


# ------------------------------------- mid-epoch preemption (host data mode)


HOST_ARGS = [
    "--synthetic-data",
    "--limit-examples", "512",   # 460 train examples -> 14 steps/epoch @32
    "--batch-size", "32",
    "--epoch", "2",
    "--data-mode", "host",
    "--host-chunk-steps", "2",
    "--workers", "0",
    "--save-last-min-secs", "0",
    "--no-progress",
    "--seed", "7",
    "--eval-step", "1000",
]


def test_host_mode_mid_epoch_preempt_drains_and_resumes_exactly(tmp_path):
    """Chunk-boundary preemption polling (ROADMAP follow-on from PR 2): the
    drain no longer waits for the epoch boundary, the checkpoint records the
    in-progress epoch's step count, and the resumed attempt fast-forwards
    past it — final params match an uninterrupted run."""
    root = tmp_path / "faulted"
    argv = HOST_ARGS + [
        "--ckpt-path", str(root), "--fault-plan", "preempt@epoch=0:step=4",
    ]
    trainer = Trainer(
        load_config("tpu", argv=argv), model=TinyNet(num_classes=100)
    )
    with pytest.raises(Preempted) as exc:
        trainer.fit()
    trainer.close()
    assert exc.value.epoch == 0 and exc.value.step == 4
    manifest = read_manifest(root / "version-0" / "last.ckpt")
    assert manifest["epoch"] == -1  # no epoch completed yet
    assert manifest["epoch_in_progress"] == 0
    assert manifest["epoch_steps_done"] == 4
    records = load_goodput_records(root / "version-0" / "goodput.jsonl")
    assert records[0]["preempted"] is True

    # relaunch (fault plan intact, as a supervisor would): resumes INTO
    # epoch 0 at step 4, does not re-fire the consumed preemption
    resumed = Trainer(
        load_config("tpu", argv=argv + ["--auto-resume"]),
        model=TinyNet(num_classes=100),
    )
    assert resumed.start_epoch == 0
    assert resumed._resume_step_offset == 4
    resumed.fit()
    resumed.close()
    assert read_manifest(root / "version-0" / "last.ckpt")["epoch"] == 1

    clean_root = tmp_path / "clean"
    clean = Trainer(
        load_config("tpu", argv=HOST_ARGS + ["--ckpt-path", str(clean_root)]),
        model=TinyNet(num_classes=100),
    )
    clean.fit()
    clean.close()
    _, resumed_params = _last_ckpt_params(root)
    _, clean_params = _last_ckpt_params(clean_root)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        resumed_params, clean_params,
    )


DEVICE_ARGS = [
    "--synthetic-data",
    "--limit-examples", "512",   # 460 train examples -> 14 steps/epoch @32
    "--batch-size", "32",
    "--epoch", "2",
    "--device-chunk-steps", "2",
    "--save-last-min-secs", "0",
    "--no-progress",
    "--seed", "7",
    "--eval-step", "1000",
]


def test_device_mode_mid_epoch_preempt_drains_and_resumes_exactly(tmp_path):
    """ISSUE 4 acceptance: device data mode gains the same chunk-boundary
    preemption drain host mode has — with ``--device-chunk-steps`` set, a
    mid-epoch ``preempt@epoch=K:step=S`` drains at the next chunk boundary
    (grace window = one chunk, not one epoch), the manifest records the
    steps done, the relaunch fast-forwards the epoch permutation past them,
    and final params match an uninterrupted run."""
    root = tmp_path / "faulted"
    argv = DEVICE_ARGS + [
        "--ckpt-path", str(root), "--fault-plan", "preempt@epoch=0:step=4",
    ]
    trainer = Trainer(
        load_config("tpu", argv=argv), model=TinyNet(num_classes=100)
    )
    with pytest.raises(Preempted) as exc:
        trainer.fit()
    trainer.close()
    assert exc.value.epoch == 0 and exc.value.step == 4
    manifest = read_manifest(root / "version-0" / "last.ckpt")
    assert manifest["epoch"] == -1  # no epoch completed yet
    assert manifest["epoch_in_progress"] == 0
    assert manifest["epoch_steps_done"] == 4

    # relaunch (fault plan intact, as a supervisor would): resumes INTO
    # epoch 0 at step 4, does not re-fire the consumed preemption
    resumed = Trainer(
        load_config("tpu", argv=argv + ["--auto-resume"]),
        model=TinyNet(num_classes=100),
    )
    assert resumed.start_epoch == 0
    assert resumed._resume_step_offset == 4
    resumed.fit()
    resumed.close()
    assert read_manifest(root / "version-0" / "last.ckpt")["epoch"] == 1

    clean_root = tmp_path / "clean"
    clean = Trainer(
        load_config("tpu", argv=DEVICE_ARGS + ["--ckpt-path", str(clean_root)]),
        model=TinyNet(num_classes=100),
    )
    clean.fit()
    clean.close()
    _, resumed_params = _last_ckpt_params(root)
    _, clean_params = _last_ckpt_params(clean_root)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        resumed_params, clean_params,
    )


def test_host_mode_final_chunk_preempt_fires_at_boundary(tmp_path):
    """A step event landing in the epoch's FINAL chunk (the mid-epoch poll
    stops one boundary early) must fire at the epoch boundary as a normal
    end-of-epoch preemption — never be silently dropped."""
    argv = HOST_ARGS + [
        "--ckpt-path", str(tmp_path), "--fault-plan", "preempt@epoch=0:step=13",
    ]
    trainer = Trainer(
        load_config("tpu", argv=argv), model=TinyNet(num_classes=100)
    )
    with pytest.raises(Preempted) as exc:
        trainer.fit()
    trainer.close()
    assert exc.value.epoch == 0  # whole epoch completed, boundary drain
    manifest = read_manifest(tmp_path / "version-0" / "last.ckpt")
    assert manifest["epoch"] == 0
    assert "epoch_in_progress" not in manifest


def test_resume_progress_marker_is_manifest_only(tmp_path, mesh):
    """The supervisor's per-attempt probe must not read/hash the payload:
    the marker comes from the manifest, and moves when the checkpoint
    does."""
    from distributed_training_comparison_tpu.train import save_resume_state
    from distributed_training_comparison_tpu.train.checkpoint import (
        find_version_dir,
        resume_progress_marker,
    )

    assert resume_progress_marker(tmp_path) is None
    state = _fresh_state(mesh)
    vdir = find_version_dir(tmp_path)
    save_resume_state(vdir, state, epoch=0, best_acc=1.0)
    m0 = resume_progress_marker(tmp_path)
    assert m0 is not None and m0[3] == 0  # manifest epoch
    save_resume_state(vdir, state, epoch=1, best_acc=1.0)
    m1 = resume_progress_marker(tmp_path)
    assert m1 != m0 and m1[3] == 1  # marker moved with progress


# ------------------------------------------------- supervisor progress probe


def test_supervisor_progress_spares_budget_and_resets_backoff():
    """Crashed attempts whose durable checkpoint ADVANCED (health rollbacks
    kept writing progress) must not consume --max-restarts, and the crash
    backoff restarts from its base instead of compounding."""
    rcs = iter([1, 1, 1, 0])
    markers = iter([None, ("ck", 1), ("ck", 2), ("ck", 3)])
    sleeps = []
    sup = Supervisor(
        ["true"],
        max_restarts=1,  # would die after 1 restart without the probe
        backoff_base=0.5,
        runner=lambda cmd, env: next(rcs),
        sleep=sleeps.append,
        log=lambda msg: None,
        progress=lambda: next(markers),
    )
    summary = sup.run()
    assert summary["final_rc"] == 0 and summary["restarts"] == 3
    assert summary["progress_restarts"] == 3
    assert sleeps == [0.5, 0.5, 0.5]  # backoff never compounded
    assert all(a["progress"] for a in summary["attempts"][:3])


def test_supervisor_without_progress_still_budgets():
    """A run stuck at the same checkpoint exhausts the budget as before."""
    sup = Supervisor(
        ["true"],
        max_restarts=1,
        backoff_base=0.01,
        runner=lambda cmd, env: 9,
        sleep=lambda s: None,
        log=lambda msg: None,
        progress=lambda: ("ck", 1),  # never moves
    )
    summary = sup.run()
    assert summary["final_rc"] == 9
    assert len(summary["attempts"]) == 2  # initial + 1 budgeted restart
    assert summary["progress_restarts"] == 0


def test_supervisor_preempt_budget_unchanged_with_probe():
    """Preemptions keep PR-2 semantics (budgeted, no backoff) even when a
    progress probe is wired."""
    markers = iter([None, ("ck", 1), ("ck", 2)])
    sup = Supervisor(
        ["true"],
        max_restarts=1,
        runner=lambda cmd, env: EXIT_PREEMPTED,
        sleep=lambda s: None,
        log=lambda msg: None,
        progress=lambda: next(markers),
    )
    summary = sup.run()
    assert len(summary["attempts"]) == 2 and summary["preemptions"] == 2


# ------------------------------------------------ goodput/writer satellites


def test_goodput_transfer_and_rollback_aggregation():
    meter = GoodputMeter()
    meter.add("step", 10.0)
    moved = meter.transfer("step", "rollback", 4.0)
    assert moved == 4.0
    assert meter.seconds["step"] == 6.0 and meter.seconds["rollback"] == 4.0
    assert meter.transfer("step", "rollback", 100.0) == 6.0  # clamped
    summary = meter.summary()
    assert summary["rollback_s"] == 10.0 and summary["step_s"] == 0.0

    report = aggregate_goodput(
        [
            {
                "step_s": 6.0, "rollback_s": 2.0, "wall_s": 10.0,
                "ckpt_writer": {"busy_s": 1.5},
                "health": {"rollbacks": 2, "skipped_steps": 3},
            },
            {"step_s": 4.0, "wall_s": 5.0},  # pre-health record: still sums
        ]
    )
    assert report["phase_totals_s"]["rollback"] == 2.0
    assert report["ckpt_writer_busy_s"] == 1.5
    assert report["health"]["rollbacks"] == 2
    assert report["health"]["skipped_steps"] == 3
    assert report["goodput_frac"] == pytest.approx(10.0 / 15.0, abs=1e-4)


def test_async_checkpointer_busy_gauge():
    import time as _time

    from distributed_training_comparison_tpu.train import AsyncCheckpointer

    writer = AsyncCheckpointer()
    try:
        writer.submit(lambda: _time.sleep(0.05), key="a")
        writer.wait()
        stats = writer.stats()
        assert stats["busy_s"] >= 0.04
        assert 0.0 < stats["busy_frac"] <= 1.0
        assert stats["alive_s"] >= stats["busy_s"]
    finally:
        writer.close()


# --------------------------------------------------------- config + tooling


def test_health_flags_defaults_and_validation():
    hp = load_config("tpu", ["--synthetic-data"])
    assert hp.health is True and hp.health_window == 64
    assert hp.health_bad_steps == 3 and hp.health_desync_every == 1
    hp = load_config("tpu", ["--no-health"])
    assert hp.health is False
    for bad in (
        ["--health-bad-steps", "0"],
        ["--health-window", "2"],
        ["--health-max-rollbacks", "-1"],
        ["--health-desync-every", "-1"],
    ):
        with pytest.raises(SystemExit):
            load_config("tpu", bad)


def test_health_report_tool_summarizes_events(tmp_path):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    import health_report

    events = [
        {"kind": "skip", "epoch": 1, "count": 3},
        {"kind": "spike", "epoch": 2, "count": 2},
        {"kind": "rollback", "epoch": 2, "to_epoch": 2,
         "wasted_steps": 18, "wasted_s": 1.5},
        {"kind": "desync", "epoch": 3},
    ]
    summary = health_report.summarize_events(events)
    assert summary["skipped_steps"] == 3 and summary["spike_steps"] == 2
    assert summary["rollbacks"] == 1 and summary["desyncs"] == 1
    assert summary["rollback_wasted_steps"] == 18
    path = tmp_path / "health.jsonl"
    path.write_text("\n".join(json.dumps(e) for e in events) + "\n{torn")
    table = health_report.format_table([("run", health_report.load_report(path))])
    assert "rollbk" in table and "run" in table


@pytest.mark.health
@pytest.mark.slow
def test_bench_health_leg_writes_report(tmp_path):
    """bench.py --health end-to-end (tiny model, small sizing): HEALTH.json
    carries the skip/rollback counts and the goodput split including the
    rollback waste."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent))
    import bench

    out = tmp_path / "HEALTH.json"
    record = bench.bench_health(
        out_path=str(out),
        trainer_model=TinyNet(num_classes=100),
        extra_argv=("--limit-examples", "640", "--epoch", "4"),
    )
    assert out.exists()
    assert record["rollbacks"] == 2 and record["skipped_steps"] == 3
    assert record["goodput"]["rollback_s"] > 0
    assert record["goodput"]["goodput_frac"] > 0
    assert record["events_check_rc"] == 0  # the capture self-validated
