"""Closed-loop autopilot tests (ISSUE 13): alert firings drive supervisor
actions, observably and rate-limited, plus the satellites that ride along
(fault-plan conflict rejection, fleet-wide quarantine persistence, the
chaos scenario catalog, ``run_report --policy``).

The load-bearing properties pinned here:

- the ``--policy`` grammar compiles (and malformed rules / rules whose
  trigger names no alert die at the CLI);
- a firing alert runs its bound action exactly once, with per-rule
  cooldowns and the per-attempt budget bounding a flap/storm, and EVERY
  decision — suppressed or acted — lands as a ``policy`` event;
- dry-run mode provably takes no action while logging (and arming the
  same cooldown/budget) as act mode would;
- the supervisor executors write the SAME marker/request files an
  operator/scheduler uses, and ``run_report --policy`` flags a requested
  action that never completed;
- policy events never count as liveness (the PR-7 self-revival flap,
  inverted and pinned for the autopilot);
- the e2e loop: an injected persistent straggler fires its alert, the
  policy drains the host, the world shrinks, and the run completes with
  params allclose to an uninterrupted baseline.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))

import run_report  # noqa: E402

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.obs.heartbeat import (
    FleetWatcher,
    LivenessTracker,
)
from distributed_training_comparison_tpu.ops import policy as P
from distributed_training_comparison_tpu.resilience import (
    CHAOS_SCENARIOS,
    FaultPlan,
    FaultSpecError,
    Supervisor,
    check_chaos_expectations,
    read_manifest,
)
from distributed_training_comparison_tpu.resilience.ckpt_io import (
    quarantine_sidecar_path,
    union_quarantine,
    write_quarantine_sidecar,
)

WORKER = Path(__file__).parent / "fleet_pool_worker.py"


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    monkeypatch.delenv(obs.RUN_ID_ENV, raising=False)
    monkeypatch.delenv(obs.ATTEMPT_ENV, raising=False)
    monkeypatch.delenv("DTC_EMU_SLOW_DISPATCH_S", raising=False)
    obs.reset()
    yield
    obs.reset()


class FakeBus:
    def __init__(self):
        self.events = []

    def emit(self, kind, **payload):
        ev = {"kind": kind, "payload": payload}
        self.events.append(ev)
        return ev

    def states(self, kind="policy"):
        return [
            e["payload"]["state"] for e in self.events if e["kind"] == kind
        ]


def _alert(spec="m:p95>1:for=1", state="firing", source="p1", metric="m"):
    return {
        "kind": "alert",
        "payload": {
            "spec": spec, "metric": metric, "state": state,
            "source": source, "value": 42.0,
        },
    }


# ------------------------------------------------------------- grammar


def test_policy_spec_parse_roundtrip():
    r = P.PolicyRule.parse(
        "step/dispatch_s:p95>30:for=2 -> drain_host:cooldown=120"
    )
    assert r.trigger == "step/dispatch_s:p95>30:for=2"
    assert r.action == "drain_host"
    assert r.cooldown_s == 120.0
    # default cooldown, whitespace tolerated
    r2 = P.PolicyRule.parse("train/loss:p95>50->rollback")
    assert r2.action == "rollback"
    assert r2.cooldown_s == P.DEFAULT_COOLDOWN_S


@pytest.mark.parametrize(
    "bad",
    [
        "no arrow here",
        "-> rollback",
        "m:p95>1 ->",
        "m:p95>1 -> explode",
        "m:p95>1 -> rollback:cooldown=abc",
        "m:p95>1 -> rollback:cooldown=-5",
        "m:p95>1 -> rollback:backoff=3",
    ],
)
def test_policy_spec_rejects_malformed(bad):
    with pytest.raises(P.PolicySpecError):
        P.PolicyRule.parse(bad)


def test_policy_rule_matches_spec_or_metric():
    by_spec = P.PolicyRule.parse("m:p95>1:for=2 -> rollback")
    assert by_spec.matches({"spec": "m:p95>1:for=2", "metric": "m"})
    assert not by_spec.matches({"spec": "m:p95>9", "metric": "m:p95>1"})
    by_metric = P.PolicyRule.parse("train/loss -> rollback")
    assert by_metric.matches({"spec": "train/loss:p95>1", "metric": "train/loss"})
    assert not by_metric.matches({"spec": "x", "metric": "train/grad_norm"})


def test_validate_policy_rules_needs_a_firing_alert():
    from distributed_training_comparison_tpu.obs.alerts import parse_alert_specs

    alerts = parse_alert_specs(["train/loss:p95>50:for=1"])
    P.validate_policy_rules(
        P.parse_policy_specs(["train/loss:p95>50:for=1 -> rollback"]), alerts
    )
    P.validate_policy_rules(  # metric-name trigger also resolves
        P.parse_policy_specs(["train/loss -> rollback"]), alerts
    )
    with pytest.raises(P.PolicySpecError):
        P.validate_policy_rules(
            P.parse_policy_specs(["train/grad_norm:p95>1 -> rollback"]),
            alerts,
        )


def test_config_rejects_policy_without_matching_alert():
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--policy", "m:p95>1 -> rollback"])
    hp = load_config(
        "tpu",
        argv=[
            "--alert", "m:p95>1", "--policy", "m:p95>1 -> rollback",
            "--policy-mode", "act",
        ],
    )
    assert hp.policy_mode == "act"
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--policy-max-actions", "0"])


# -------------------------------------------------------------- engine


def test_engine_acts_once_and_emits_requested_completed():
    bus = FakeBus()
    calls = []
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m:p95>1:for=1 -> drain_host:cooldown=60"]),
        bus=bus, mode="act", clock=lambda: 0.0,
    )
    eng.bind("drain_host", lambda d: calls.append(d) or {"host": 1})
    eng.observe_event(_alert(spec="m:p95>1:for=1"))
    assert bus.states() == ["requested", "completed"]
    assert len(calls) == 1
    assert calls[0]["alert_source"] == "p1"
    done = [e for e in bus.events if e["payload"]["state"] == "completed"]
    assert done[0]["payload"]["host"] == 1
    # resolved transitions and foreign kinds never trigger
    eng.observe_event(_alert(spec="m:p95>1:for=1", state="resolved"))
    eng.observe_event({"kind": "metrics", "payload": {}})
    assert len(calls) == 1


def test_engine_cooldown_bounds_a_flapping_alert():
    bus = FakeBus()
    clock = [0.0]
    calls = []
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> drain_host:cooldown=100"]),
        bus=bus, mode="act", clock=lambda: clock[0],
    )
    eng.bind("drain_host", lambda d: calls.append(d) or {})
    eng.observe_event(_alert())
    clock[0] = 50.0
    eng.observe_event(_alert())  # flap inside the window: suppressed
    assert bus.states() == ["requested", "completed", "cooldown"]
    cd = bus.events[-1]["payload"]
    assert cd["cooldown_remaining_s"] == pytest.approx(50.0)
    clock[0] = 150.0
    eng.observe_event(_alert())  # window passed: acts again
    assert len(calls) == 2


def test_engine_budget_bounds_a_storm_and_regrants_per_attempt():
    bus = FakeBus()
    calls = []
    eng = P.PolicyEngine(
        # distinct rules so the cooldown cannot be what stops the storm
        P.parse_policy_specs(
            ["a -> rollback:cooldown=0", "b -> rollback:cooldown=0"]
        ),
        bus=bus, mode="act", max_actions=1, clock=lambda: 1e9,
    )
    eng.bind("rollback", lambda d: calls.append(d) or {})
    eng.observe_event(_alert(metric="a"))
    eng.observe_event(_alert(metric="b"))
    assert len(calls) == 1
    assert bus.states()[-1] == "budget"
    # a new attempt re-grants; the same attempt index does NOT (the
    # explicit supervisor call and the tailed attempt_start both land)
    eng.observe_event({"kind": "attempt_start", "payload": {"attempt": 0}})
    eng.observe_event(_alert(metric="b"))
    assert bus.states()[-1] == "budget"
    eng.observe_event({"kind": "attempt_start", "payload": {"attempt": 1}})
    eng.observe_event(_alert(metric="b"))
    assert len(calls) == 2


def test_engine_budget_regrants_on_the_clock_without_attempts():
    """A session with no attempt boundaries (serving, unsupervised runs)
    re-grants the budget every BUDGET_WINDOW_S: the cap rate-limits a
    storm, it must not permanently disable the autopilot — a serve
    session's fifth recompile storm still gets its re-warm."""
    bus = FakeBus()
    calls = []
    clock = [0.0]
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> rewarm_serve:cooldown=0"]),
        bus=bus, mode="act", max_actions=1, clock=lambda: clock[0],
    )
    eng.bind("rewarm_serve", lambda d: calls.append(d) or {})
    eng.observe_event(_alert())
    clock[0] = 10.0
    eng.observe_event(_alert())  # inside the window: budget-suppressed
    assert len(calls) == 1 and bus.states()[-1] == "budget"
    clock[0] = P.BUDGET_WINDOW_S + 1.0
    eng.observe_event(_alert())  # window rolled: the budget re-granted
    assert len(calls) == 2 and bus.states()[-1] == "completed"


def test_engine_dry_run_logs_without_acting_and_arms_cooldown():
    bus = FakeBus()
    logged = []
    calls = []
    clock = [0.0]
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> drain_host:cooldown=100"]),
        bus=bus, mode="dry-run", clock=lambda: clock[0],
        log=logged.append,
    )
    eng.bind("drain_host", lambda d: calls.append(d) or {})
    eng.observe_event(_alert())
    assert calls == []  # provably no action
    assert bus.states() == ["dry_run"]
    assert bus.events[0]["payload"]["dry_run"] is True
    assert any("would run drain_host" in m for m in logged)
    clock[0] = 50.0
    eng.observe_event(_alert())  # the dry decision armed the cooldown too
    assert bus.states() == ["dry_run", "cooldown"]


def test_engine_mode_off_is_inert():
    bus = FakeBus()
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> rollback"]), bus=bus, mode="off"
    )
    eng.observe_event(_alert())
    assert bus.events == []


def test_engine_unbound_failed_and_deferred_states():
    bus = FakeBus()
    eng = P.PolicyEngine(
        P.parse_policy_specs(
            ["a -> drain_host:cooldown=0", "b -> rollback:cooldown=0",
             "c -> rewarm_serve:cooldown=0"]
        ),
        bus=bus, mode="act", clock=lambda: 1e9,
    )

    def boom(decision):
        raise P.PolicyActionError("nope")

    eng.bind("drain_host", boom)
    eng.bind("rollback", lambda d: {"deferred": True})
    # no executor for rewarm_serve in this process
    eng.observe_event(_alert(metric="a"))
    assert bus.states() == ["requested", "failed"]
    assert bus.events[-1]["payload"]["error"] == "nope"
    eng.observe_event(_alert(metric="b"))
    assert bus.states()[-1] == "requested"  # completion comes from afar
    assert [p["action"] for p in eng.pending()] == ["rollback"]
    eng.observe_event(_alert(metric="c"))
    assert bus.states()[-1] == "unbound"
    s = eng.summary()
    assert s["by_state"]["failed"] == 1 and s["by_state"]["unbound"] == 1
    assert s["pending"] and s["mode"] == "act"
    # ... and when the deferred outcome arrives (the watcher tails the
    # applying process's events back through observe_event), the pending
    # ledger converges with the stream
    eng.observe_event({
        "kind": "policy",
        "payload": {"state": "completed", "id": s["pending"][0]},
    })
    assert eng.pending() == [] and eng.summary()["pending"] == []


def test_coalesced_is_terminal_but_not_completed():
    """A decision folded into an already-queued request must close its
    own id (the pending gate passes) WITHOUT counting as a performed
    action — the queued request's id carries the real outcome."""
    bus = FakeBus()
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> rollback:cooldown=0"]),
        bus=bus, mode="act", clock=lambda: 1e9,
    )
    eng.bind("rollback", lambda d: {"coalesced": True})
    eng.observe_event(_alert())
    assert bus.states() == ["requested", "coalesced"]
    assert eng.pending() == []
    # offline: coalesced terminates its requested id for the gate too
    evs = [
        _policy_event("requested", "x-1", 1.0),
        _policy_event("coalesced", "x-1", 2.0),
    ]
    assert P.pending_actions(evs) == []


def test_decision_ids_are_unique_across_engines():
    """Two supervisor sessions over one ckpt root must not mint colliding
    ids — the pending gate would pair a new session's 'requested' with an
    old session's terminal event and miss a lost action."""
    a = P.PolicyEngine(P.parse_policy_specs(["m -> rollback"]), mode="act")
    b = P.PolicyEngine(P.parse_policy_specs(["m -> rollback"]), mode="act")
    a.bind("rollback", lambda d: {"deferred": True})
    b.bind("rollback", lambda d: {"deferred": True})
    a.observe_event(_alert())
    b.observe_event(_alert())
    assert a.pending()[0]["id"] != b.pending()[0]["id"]


def test_engine_unbound_spends_neither_budget_nor_cooldown():
    """A rule whose action has no executor here can do nothing — firing
    it must not starve the runnable rules of the shared budget, nor arm
    its own cooldown (binding the executor later must not find a rule
    stuck in a cooldown it never earned)."""
    bus = FakeBus()
    calls = []
    clock = [0.0]
    eng = P.PolicyEngine(
        P.parse_policy_specs(
            ["a -> rewarm_serve:cooldown=100", "b -> rollback:cooldown=0"]
        ),
        bus=bus, mode="act", max_actions=1, clock=lambda: clock[0],
    )
    eng.bind("rollback", lambda d: calls.append(d) or {})
    for _ in range(3):
        eng.observe_event(_alert(metric="a"))  # unbound: free
    eng.observe_event(_alert(metric="b"))
    assert len(calls) == 1  # the runnable rule still had its budget
    assert bus.states() == [
        "unbound", "unbound", "unbound", "requested", "completed",
    ]
    # bind it late: no phantom cooldown from the unbound decisions
    eng.observe_event({"kind": "attempt_start", "payload": {"attempt": 1}})
    eng.bind("rewarm_serve", lambda d: calls.append(d) or {})
    eng.observe_event(_alert(metric="a"))
    assert bus.states()[-1] == "completed" and len(calls) == 2


def test_bad_mode_rejected():
    with pytest.raises(P.PolicySpecError):
        P.PolicyEngine([], mode="yolo")


def test_engine_ignores_replayed_history():
    """The supervisor's watcher tails event files from byte 0: a restart
    over an existing ckpt root replays every old alert firing.  Acting on
    one would drain a now-healthy host or abort a fresh run over a
    previous session's tripwire — events older than the engine are
    history, not findings."""
    import time as _time

    bus = FakeBus()
    calls = []
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> drain_host:cooldown=0"]),
        bus=bus, mode="act", clock=lambda: 1e9,
    )
    eng.bind("drain_host", lambda d: calls.append(d) or {})
    stale = dict(_alert(), t_wall=_time.time() - 3600.0)
    eng.observe_event(stale)
    assert calls == [] and bus.events == []
    fresh = dict(_alert(), t_wall=_time.time() + 1.0)
    eng.observe_event(fresh)
    assert len(calls) == 1


def test_engine_dry_run_previews_unbound_without_spending():
    """Executors are bound identically in both modes, so dry-run must
    classify an unbound action exactly as act would — and spend neither
    budget nor cooldown on it, or the previewed suppressions would not
    be the ones act mode applies."""
    bus = FakeBus()
    eng = P.PolicyEngine(
        P.parse_policy_specs(
            ["a -> drain_host:cooldown=0", "b -> rollback:cooldown=0"]
        ),
        bus=bus, mode="dry-run", max_actions=1, clock=lambda: 1e9,
    )
    eng.bind("rollback", lambda d: {})  # drain_host deliberately unbound
    for _ in range(3):
        eng.observe_event(_alert(metric="a"))
    eng.observe_event(_alert(metric="b"))
    assert bus.states() == ["unbound", "unbound", "unbound", "dry_run"]


# ----------------------------------------------------- request channel


def test_request_channel_roundtrip_and_torn_request(tmp_path):
    path = P.write_action_request(
        tmp_path, "rollback", {"id": "a0-1", "rule": "r"}
    )
    assert path.name == "policy-rollback.req"
    poller = P.PolicyRequestPoller(tmp_path)
    reqs = poller.poll()
    assert reqs == [{"id": "a0-1", "rule": "r", "action": "rollback"}]
    assert poller.poll() == []  # consumed
    # torn/garbage request still consumes and names its action
    (tmp_path / "fleet" / "policy-abort_with_evidence.req").write_text("{tor")
    reqs = poller.poll()
    assert reqs == [{"action": "abort_with_evidence"}]
    with pytest.raises(P.PolicyActionError):
        P.write_action_request(tmp_path, "drain_host", {})


def test_supervisor_actions_write_markers_and_requests(tmp_path):
    stops = []
    acts = P.supervisor_actions(
        tmp_path, fleet_hosts=2, request_stop=stops.append
    )
    # rank -> host mapping through the live status file (after a shrink
    # rank 0 may live on host 1)
    fleet = tmp_path / "fleet"
    fleet.mkdir()
    (fleet / "status.json").write_text(json.dumps({"hosts": [1]}))
    res = acts["drain_host"]({"alert_source": "p0", "rule": "r", "id": "x"})
    assert res["host"] == 1
    marker = fleet / "host-1.down"
    assert marker.exists()
    assert json.loads(marker.read_text())["by"] == "policy"
    # no status file: the rank is the host
    (fleet / "status.json").unlink()
    res = acts["drain_host"]({"alert_source": "p0"})
    assert res["host"] == 0
    # a fleet-aggregate alert names no host
    with pytest.raises(P.PolicyActionError):
        acts["drain_host"]({"alert_source": "fleet"})
    # deferred actions land as request files; abort also stops the loop
    assert acts["rollback"]({"id": "a0-2"})["deferred"] is True
    assert (fleet / "policy-rollback.req").exists()
    # an unconsumed request wins: the second decision coalesces into it
    # (completing immediately) instead of overwriting/orphaning its id
    again = acts["rollback"]({"id": "a0-9"})
    assert again == {"coalesced": True}
    assert json.loads(
        (fleet / "policy-rollback.req").read_text()
    )["id"] == "a0-2"
    assert acts["abort_with_evidence"]({"id": "a0-3", "rule": "r"})[
        "deferred"
    ] is True
    assert (fleet / "policy-abort_with_evidence.req").exists()
    assert stops and "abort_with_evidence" in stops[0]
    # rewarm_serve is deliberately ABSENT: an in-process serving action
    # left genuinely unbound supervisor-side, so a misplaced rewarm rule
    # reports unbound without burning cooldown or the shared budget
    assert "rewarm_serve" not in acts
    # and without an elastic fleet there is nothing to drain
    solo = P.supervisor_actions(tmp_path, fleet_hosts=0)
    with pytest.raises(P.PolicyActionError):
        solo["drain_host"]({"alert_source": "p0"})


def test_emit_completion_pairs_with_requested():
    bus = FakeBus()
    P.emit_completion(
        bus, {"action": "rollback", "id": "a0-1", "rule": "r"},
        from_epoch=3, to_epoch=2,
    )
    P.emit_completion(
        bus, {"action": "rollback", "id": "a0-2"}, ok=False, error="why"
    )
    states = bus.states()
    assert states == ["completed", "failed"]
    assert bus.events[1]["payload"]["error"] == "why"


# ------------------------------------------------- watcher + liveness


def test_fleet_watcher_feeds_policy_from_the_tail(tmp_path):
    bus = obs.EventBus(run_id="x" * 16, persist=True)
    bus.bind_dir(tmp_path)
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> drain_host:cooldown=0"]),
        bus=None, mode="dry-run", clock=lambda: 1e9,
    )
    eng.bind("drain_host", lambda d: {})
    watcher = FleetWatcher(tmp_path, FakeBus(), policy=eng, poll_s=0.05)
    src = obs.EventBus(run_id="y" * 16, process_index=1)
    src.bind_dir(tmp_path)
    src.emit("alert", spec="s", metric="m", state="firing", source="p1")
    watcher.step()
    assert [d["state"] for d in eng.decisions] == ["dry_run"]
    bus.close()
    src.close()


def test_policy_events_are_not_liveness():
    """The PR-7 flap, inverted for the autopilot: a policy event about a
    host must never count as that host being alive."""
    tracker = LivenessTracker(heartbeat_s=1.0)
    tracker.observe(
        {"kind": "policy", "process_index": 1, "payload": {}}, now=0.0
    )
    assert tracker.states() == {}
    tracker.observe({"kind": "heartbeat", "process_index": 1}, now=0.0)
    tracker.observe(
        {"kind": "chaos", "process_index": 1, "payload": {}}, now=100.0
    )
    # the chaos stamp did not refresh host 1: it is long dead by now
    assert [f["state"] for f in tracker.check(now=100.0)] == ["dead"]


# --------------------------------------------------- supervisor stop


def test_supervisor_request_stop_breaks_without_relaunch():
    rcs = [1, 1, 1]
    seen = []
    events = []

    def runner(cmd, env):
        seen.append(list(cmd))
        return rcs[len(seen) - 1]

    sup = Supervisor(
        ["train"], runner=runner, max_restarts=5,
        sleep=lambda s: None, log=lambda m: None,
        events=lambda kind, **p: events.append((kind, p)),
    )
    sup.request_stop("policy abort_with_evidence (rule)")
    summary = sup.run()
    assert len(seen) == 1  # the in-flight attempt finished; no relaunch
    assert summary["final_rc"] == 1 and summary["restarts"] == 0
    give_up = [p for k, p in events if k == "give_up"]
    assert give_up and "abort_with_evidence" in give_up[0]["reason"]


# ------------------------------------------------------- crash evidence


def test_dump_crash_carries_evidence(tmp_path):
    bus = obs.EventBus(run_id="e" * 16)
    bus.emit("alert", state="firing", spec="s")
    path = bus.dump_crash(
        "policy abort", directory=tmp_path,
        evidence={"alert_timeline": [{"kind": "alert"}], "policy_timeline": []},
    )
    dump = json.loads(Path(path).read_text())
    assert dump["evidence"]["alert_timeline"] == [{"kind": "alert"}]
    bus.close()


# -------------------------------------------------- run_report --policy


def _policy_event(state, pid, t, action="rollback"):
    return {
        "v": 1, "run_id": "r" * 16, "attempt": 0, "process_index": 0,
        "t_wall": t, "t_mono": t, "kind": "policy",
        "payload": {
            "state": state, "id": pid, "action": action, "rule": "m -> x",
        },
    }


def _control_event(state, pid, t, action="rollback", **extra):
    return {
        "v": 1, "run_id": "r" * 16, "attempt": 0, "process_index": 0,
        "t_wall": t, "t_mono": t, "kind": "control",
        "payload": {
            "state": state, "id": pid, "action": action,
            "boundary": "chunk", "mid_epoch": True,
            "t_decide": t - 1.0, "t_apply": t, "ttm_s": 1.0, **extra,
        },
    }


def test_run_report_policy_exit_codes(tmp_path, capsys):
    events = tmp_path / "events.jsonl"
    # completed pair + its applied control event + an informational
    # dry-run: rc 0
    rows = [
        _policy_event("requested", "a0-1", 1.0),
        _policy_event("completed", "a0-1", 2.0),
        _control_event("applied", "a0-1", 2.0, steps_since_decide=2),
        dict(_policy_event("dry_run", "a0-2", 3.0), payload={
            "state": "dry_run", "id": "a0-2", "action": "drain_host",
            "rule": "m -> drain_host", "dry_run": True,
        }),
    ]
    events.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert run_report.main([str(tmp_path), "--policy"]) == 0
    out = capsys.readouterr().out
    assert "COMPLETED" in out and "no action taken" in out
    assert "APPLIED" in out and "ttm=1.000s" in out
    # a requested action with no outcome anywhere in the stream: rc 1
    rows.append(_policy_event("requested", "a0-3", 4.0))
    events.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert run_report.main([str(tmp_path), "--policy"]) == 1
    assert "STILL PENDING" in capsys.readouterr().out
    # an acted decision that completed but never reached an 'applied'
    # control event: the decide->apply trail broke mid-way, rc 1
    rows = [
        _policy_event("requested", "b0-1", 1.0),
        _policy_event("completed", "b0-1", 2.0),
    ]
    events.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert run_report.main([str(tmp_path), "--policy"]) == 1
    assert "NEVER APPLIED" in capsys.readouterr().out
    # no policy events at all is healthy; an empty root is rc 2
    events.write_text(json.dumps(_policy_event("x", "y", 0.0)).replace(
        '"policy"', '"metrics"'
    ) + "\n")
    assert run_report.main([str(tmp_path), "--policy"]) == 0
    empty = tmp_path / "empty"
    empty.mkdir()
    assert run_report.main([str(empty), "--policy"]) == 2


def test_pending_actions_joins_across_processes():
    evs = [
        _policy_event("requested", "a0-1", 1.0),
        dict(_policy_event("completed", "a0-1", 2.0), process_index=1),
        _policy_event("requested", "a0-2", 3.0),
    ]
    pend = P.pending_actions(evs)
    assert [p["id"] for p in pend] == ["a0-2"]
    assert len(P.policy_timeline(evs)) == 3


# ------------------------------------------------ fault-plan conflicts


def test_fault_plan_rejects_same_kind_window_duplicates():
    # step faults: same kind + epoch conflicts whatever the step offsets
    # (the second can only fire on the contractually-clean replay)
    with pytest.raises(FaultSpecError) as e:
        FaultPlan.parse("nan_grad@epoch=1;nan_grad@epoch=1:step=4")
    assert "nan_grad@epoch=1" in str(e.value)
    assert "nan_grad@epoch=1:step=4" in str(e.value)
    with pytest.raises(FaultSpecError):
        FaultPlan.parse("loss_spike@epoch=2,loss_spike@epoch=2:scale=9")
    with pytest.raises(FaultSpecError):
        FaultPlan.parse("desync@epoch=1;desync@epoch=1")
    # boundary faults: duplicates share kind+epoch+step
    with pytest.raises(FaultSpecError):
        FaultPlan.parse("preempt@epoch=2;preempt@epoch=2")
    with pytest.raises(FaultSpecError):
        FaultPlan.parse("stall@epoch=1:secs=1;stall@epoch=1:secs=2")
    # legitimate compositions still parse
    assert FaultPlan.parse(
        "nan_grad@epoch=1;loss_spike@epoch=2;preempt@epoch=3;"
        "preempt@epoch=5:step=2;preempt@epoch=5:step=6;stall@epoch=4:secs=1"
    ) is not None
    # prob-draws are exempt (their windows are not knowable at parse time)
    assert FaultPlan.parse("preempt@prob=0.1;preempt@prob=0.2") is not None


# ---------------------------------------------------- chaos catalog


def test_chaos_catalog_is_well_formed():
    from distributed_training_comparison_tpu.obs.alerts import parse_alert_specs

    assert CHAOS_SCENARIOS, "catalog must not be empty"
    # the matrix covers its advertised axes
    joined = json.dumps(CHAOS_SCENARIOS)
    for axis in ("preempt", "nan_grad", "drain_host", "host-1.up"):
        assert axis in joined, f"matrix lost the {axis} axis"
    for name, sc in CHAOS_SCENARIOS.items():
        for field in (
            "desc", "fault_plan", "alerts", "policies", "policy_mode",
            "driver", "env", "extra_args", "expect", "require_kinds",
        ):
            assert field in sc, f"{name} missing {field}"
        alerts = parse_alert_specs(list(sc["alerts"]))
        rules = P.parse_policy_specs(list(sc["policies"]))
        P.validate_policy_rules(rules, alerts)  # triggers resolve
        if sc["fault_plan"]:
            assert FaultPlan.parse(sc["fault_plan"]) is not None
        assert sc["policy_mode"] in P.MODES
        for kind in sc["require_kinds"]:
            assert kind in obs.KNOWN_KINDS
    # dry-run is proven by a scenario that expects NOTHING to happen
    dry = CHAOS_SCENARIOS["straggler_dryrun"]["expect"]
    assert dry["resizes"] == 0 and dry["policy_completed"] == 0


def test_check_chaos_expectations_bounds():
    obs_row = {
        "final_rc": 0, "resizes": 2, "policy_completed": 1,
        "crash_dump_evidence": False,
    }
    assert check_chaos_expectations(
        {"final_rc": 0, "resizes__min": 1, "policy_completed__max": 2},
        obs_row,
    ) == []
    probs = check_chaos_expectations(
        {"final_rc_nonzero": True, "resizes": 0, "missing__min": 1,
         "crash_dump_evidence": True},
        obs_row,
    )
    assert len(probs) == 4


# ------------------------------------------- quarantine persistence


def test_quarantine_sidecar_roundtrip_and_union(tmp_path):
    assert write_quarantine_sidecar(tmp_path, 0, []) is None  # empty: no file
    p0 = write_quarantine_sidecar(tmp_path, 0, [3, 1])
    p1 = write_quarantine_sidecar(tmp_path, 1, {7, 5})
    assert p0 == quarantine_sidecar_path(tmp_path, 0)
    assert json.loads(p1.read_text()) == [5, 7]
    # manifest base + every rank's sidecar union; torn sidecars skipped
    (tmp_path / "quarantine-p2.json").write_text("{half a reco")
    assert union_quarantine(tmp_path, base=[9, 1]) == [1, 3, 5, 7, 9]
    assert union_quarantine(tmp_path) == [1, 3, 5, 7]
    assert union_quarantine(tmp_path / "nowhere", base=[2]) == [2]
    # valid JSON with drifted entries: bad values dropped, never raised
    (tmp_path / "quarantine-p3.json").write_text('[11, null, "x", "13"]')
    assert union_quarantine(tmp_path) == [1, 3, 5, 7, 11, 13]


@pytest.mark.health
def test_quarantine_union_survives_multihost_relaunch(tmp_path):
    """ROADMAP fleet residue, closed: a relaunch re-applies EVERY rank's
    quarantined example ids — the manifest's (rank 0) unioned with the
    quarantine-p*.json sidecars other ranks left next to the checkpoint —
    not just rank 0's set.  Emulated 2-host shape: a real single-process
    run quarantines its own window (manifest + its sidecar), and rank 1's
    sidecar is written at the file level, exactly what a second host
    leaves on the shared checkpoint root."""
    from distributed_training_comparison_tpu.train import Trainer
    from test_train import TinyNet

    argv = [
        "--synthetic-data", "--limit-examples", "128",
        "--batch-size", "32", "--epoch", "2",
        "--save-last-min-secs", "0", "--no-progress", "--seed", "7",
        "--data-mode", "host", "--workers", "0",
        "--ckpt-path", str(tmp_path),
        "--fault-plan", "nan_grad@epoch=1",
        "--health-quarantine", "--health-bad-steps", "3",
    ]
    trainer = Trainer(load_config("tpu", argv=argv), model=TinyNet(num_classes=100))
    trainer.fit()
    rank0 = set(trainer.train_loader.quarantined)
    trainer.close()
    assert rank0, "the fault must have quarantined rank 0's window"
    vdir = tmp_path / "version-0"
    # rank 0's own set was persisted BOTH ways
    manifest = read_manifest(vdir / "last.ckpt")
    assert set(manifest["quarantined"]) == rank0
    assert set(json.loads(quarantine_sidecar_path(vdir, 0).read_text())) == rank0
    # "host 1" condemned a disjoint window of ITS shard before the relaunch
    rank1 = {101, 102, 103} - rank0
    write_quarantine_sidecar(vdir, 1, rank1)
    resumed = Trainer(
        load_config(
            "tpu",
            argv=[
                "--synthetic-data", "--limit-examples", "128",
                "--batch-size", "32", "--epoch", "3",
                "--save-last-min-secs", "0", "--no-progress", "--seed", "7",
                "--data-mode", "host", "--workers", "0",
                "--ckpt-path", str(tmp_path), "--auto-resume",
                "--health-quarantine",
            ],
        ),
        model=TinyNet(num_classes=100),
    )
    try:
        assert set(resumed.train_loader.quarantined) == rank0 | rank1
    finally:
        resumed.close()


# ----------------------------------------------------- serve rewarm


def test_serve_rewarm_closes_a_recompile_storm():
    from distributed_training_comparison_tpu.serve import ServeEngine
    from test_train import TinyNet

    eng = ServeEngine(
        model=TinyNet(num_classes=10), buckets=(2, 4, 8),
        precision="fp32", image_size=16,
    )
    eng.warmup(buckets=[2])  # the replica's expected traffic
    assert eng.recompiled_buckets == ()
    # a flash crowd lands on an unwarmed bucket: the storm's footprint
    eng.predict_logits(np.zeros((4, 16, 16, 3), np.uint8))
    assert eng.recompiled_buckets == (4,)
    res = eng.rewarm()
    # the affected bucket plus the still-cold remainder of the ladder
    assert res["recompiled"] == [4]
    assert res["warmed"] == [4, 8]
    assert eng.recompiled_buckets == ()
    before = eng.stats()["compiles"]
    eng.predict_logits(np.zeros((8, 16, 16, 3), np.uint8))
    assert eng.stats()["compiles"] == before  # the ladder is fully warm
    assert eng.recompiled_buckets == ()
    # nothing left to warm: rewarm still succeeds (and re-arms)
    assert eng.rewarm() == {"warmed": [], "recompiled": []}


# --------------------------------------------- in-process trainer e2e


def _tiny_argv(tmp_path, extra=()):
    return [
        "--synthetic-data", "--limit-examples", "128",
        "--batch-size", "32", "--epoch", "3",
        "--save-last-min-secs", "0", "--no-progress", "--seed", "7",
        "--device-chunk-steps", "2", "--eval-step", "1000",
        "--ckpt-path", str(tmp_path), *extra,
    ]


@pytest.mark.health
def test_inprocess_policy_rollback_applies_at_epoch_boundary(tmp_path):
    """Unsupervised closed loop, rollback flavor: an in-process alert on
    the (always-breaching) loss metric fires once, the policy engine
    defers a rollback to the epoch boundary, and the trainer replays via
    the existing watchdog path — every decision on the event stream."""
    from distributed_training_comparison_tpu.train import Trainer
    from test_train import TinyNet

    hp = load_config(
        "tpu",
        argv=_tiny_argv(
            tmp_path,
            extra=[
                "--alert", "train/loss:p95>-1:for=1",
                "--policy", "train/loss:p95>-1:for=1 -> rollback:cooldown=9999",
                "--policy-mode", "act",
            ],
        ),
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    try:
        trainer.fit()
    finally:
        trainer.close()
    events = obs.load_events(tmp_path / "version-0" / "events.jsonl")
    states = [
        e["payload"]["state"] for e in events if e["kind"] == "policy"
    ]
    assert "requested" in states and "completed" in states
    rollbacks = [e for e in events if e["kind"] == "rollback"]
    assert rollbacks and "policy action" in rollbacks[0]["payload"]["reason"]
    assert P.pending_actions(events) == []
    assert run_report.main([str(tmp_path), "--policy"]) == 0
    assert run_report.main(
        [str(tmp_path), "--check", "--require-kind", "policy"]
    ) == 0


@pytest.mark.health
def test_inprocess_policy_dry_run_takes_no_action(tmp_path):
    """Same rule in the default dry-run mode: the decision is logged as a
    policy event, and provably nothing happens — no rollback, no request,
    identical epoch count."""
    from distributed_training_comparison_tpu.train import Trainer
    from test_train import TinyNet

    hp = load_config(
        "tpu",
        argv=_tiny_argv(
            tmp_path,
            extra=[
                "--alert", "train/loss:p95>-1:for=1",
                "--policy", "train/loss:p95>-1:for=1 -> rollback:cooldown=9999",
            ],
        ),
    )
    assert hp.policy_mode == "dry-run"  # the default
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    try:
        trainer.fit()
    finally:
        trainer.close()
    events = obs.load_events(tmp_path / "version-0" / "events.jsonl")
    states = [
        e["payload"]["state"] for e in events if e["kind"] == "policy"
    ]
    assert states == ["dry_run"]
    assert not any(e["kind"] == "rollback" for e in events)


@pytest.mark.health
def test_inprocess_policy_abort_attaches_evidence(tmp_path):
    """abort_with_evidence, unsupervised: the run stops orderly at the
    next epoch boundary and crash_dump.json carries the alert + policy
    timelines under 'evidence' — the post-mortem opens on WHY."""
    from distributed_training_comparison_tpu.train import Trainer
    from test_train import TinyNet

    hp = load_config(
        "tpu",
        argv=_tiny_argv(
            tmp_path,
            extra=[
                "--alert", "train/loss:p95>-1:for=1",
                "--policy",
                "train/loss:p95>-1:for=1 -> abort_with_evidence:cooldown=9999",
                "--policy-mode", "act",
            ],
        ),
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    with pytest.raises(P.PolicyAbort):
        try:
            trainer.fit()
        finally:
            trainer.close()
    dump = json.loads((tmp_path / "version-0" / "crash_dump.json").read_text())
    assert "policy abort_with_evidence" in dump["reason"]
    ev = dump["evidence"]
    assert ev["alert_timeline"] and ev["policy_timeline"]
    assert ev["request"]["rule"].endswith("abort_with_evidence:cooldown=9999")
    events = obs.load_events(tmp_path / "version-0" / "events.jsonl")
    assert any(e["kind"] == "abort" for e in events)
    assert P.pending_actions(events) == []


# --------------------------------------------------- supervised e2e


@pytest.mark.elastic
def test_e2e_policy_drains_persistent_straggler(tmp_path):
    """ISSUE 13 acceptance: a supervised 2-host fleet with a persistent
    straggler on host 1 -> the dispatch alert fires -> the POLICY (not an
    operator) writes host-1.down -> the fleet drains and re-renders a
    world-1 attempt that resumes from the verified checkpoint -> the run
    completes with params allclose to an uninterrupted baseline, every
    action traceable to its alert on the merged stream."""
    from distributed_training_comparison_tpu.resilience.faults import (
        EMU_SLOW_DISPATCH_ENV,
    )

    root = tmp_path / "run"
    goodput_json = tmp_path / "GOODPUT.json"
    cmd = [
        sys.executable, str(WORKER), "--supervise",
        "--fleet-hosts", "2", "--fleet-local-devices", "1",
        "--fleet-grace-secs", "3", "--fleet-poll-secs", "0.2",
        "--synthetic-data", "--limit-examples", "256",
        "--batch-size", "32", "--epoch", "10",
        "--no-progress", "--eval-step", "1000",
        "--save-last-min-secs", "0", "--seed", "7",
        "--device-chunk-steps", "2",
        "--heartbeat-secs", "0.2",
        "--ckpt-path", str(root),
        "--goodput-json", str(goodput_json),
        "--alert", "step/dispatch_s:p95>30:for=2",
        "--policy", "step/dispatch_s:p95>30:for=2 -> drain_host:cooldown=120",
        "--policy-mode", "act",
    ]
    env = dict(os.environ)
    env[EMU_SLOW_DISPATCH_ENV] = "60"
    proc = subprocess.run(
        cmd, cwd=WORKER.parent.parent, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=420,
    )
    assert proc.returncode == 0, (proc.stderr or "")[-3000:]
    assert "Traceback" not in (proc.stderr or ""), (proc.stderr or "")[-3000:]

    events, _files = run_report.load_run(root)
    # the policy acted exactly once: requested -> completed, naming host 1
    policy = [e["payload"] for e in events if e["kind"] == "policy"]
    assert [p["state"] for p in policy] == ["requested", "completed"]
    assert policy[1]["host"] == 1
    assert policy[0]["rule"].startswith("step/dispatch_s:p95>30")
    assert policy[0]["alert_source"] == "p1"
    assert policy[0]["dry_run"] is False
    # traceable to its triggering alert on the same stream
    firings = [
        e["payload"] for e in events
        if e["kind"] == "alert" and e["payload"]["state"] == "firing"
    ]
    assert any(
        f["spec"] == policy[0]["trigger"] and f.get("source") == "p1"
        for f in firings
    )
    # the fleet path was the operator path: drain -> shrink -> resume
    resizes = [e["payload"] for e in events if e["kind"] == "resize"]
    assert [(r["from_world"], r["to_world"], r["reason"]) for r in resizes] == [
        (2, 1, "host_lost")
    ]
    run_starts = {
        e["attempt"]: e["payload"] for e in events if e["kind"] == "run_start"
    }
    assert run_starts[1]["resumed"] is True
    # the marker the policy wrote was consumed by the fleet
    assert not (root / "fleet" / "host-1.down").exists()
    assert run_report.main([str(root), "--policy"]) == 0
    assert run_report.main(
        [str(root), "--check", "--require-kind", "policy",
         "--require-kind", "resize"]
    ) == 0
    gp = json.loads(goodput_json.read_text())
    assert gp["supervisor"]["policy"]["by_state"]["completed"] == 1

    # uninterrupted same-seed baseline on this process's devices
    from distributed_training_comparison_tpu.train import Trainer
    from fleet_pool_worker import TinyNet
    from flax import serialization
    import jax

    clean_root = tmp_path / "clean"
    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "256",
            "--batch-size", "32", "--epoch", "10",
            "--no-progress", "--eval-step", "1000",
            "--save-last-min-secs", "0", "--seed", "7",
            "--device-chunk-steps", "2",
            "--ckpt-path", str(clean_root),
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    trainer.fit()
    trainer.close()

    def final_params(r):
        raw = serialization.msgpack_restore(
            (r / "version-0" / "last.ckpt").read_bytes()
        )
        assert raw["epoch"] == 9  # all 10 epochs completed
        return raw["state"]["params"]

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        final_params(root),
        final_params(clean_root),
    )
