"""Fast (no-jit) coverage for the composed-pipeline subsystem's seams:
schedule tick arithmetic, the actionable microbatch/pipe refusals, the
pipe-axis reshard validation, per-stage straggler phase keys, the
run_report bubble table, and the synthetic (host, stage) span lanes.

The schedule NUMERICS (interleaved == 1f1b == gpipe == unpipelined) live
in tests/test_pipeline.py — they compile real meshes and are slow-marked;
everything here is pure host-side arithmetic and event processing.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.health.desync import (
    check_partial_desync,
)
from distributed_training_comparison_tpu.obs import straggler
from distributed_training_comparison_tpu.parallel.pipeline import (
    schedule_meta,
)
from distributed_training_comparison_tpu.resilience.elastic import (
    ReshardError,
    microbatch_help,
    pipeline_help,
    validate_reshard,
)

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
import run_report  # noqa: E402


# ------------------------------------------------ schedule tick arithmetic


def test_schedule_meta_1f1b_recovers_textbook_ticks():
    m = schedule_meta("1f1b", pipe=4, microbatches=8)
    assert m["ticks"] == 8 + 2 * 4 - 2
    assert m["useful_ticks"] == 8
    assert m["virtual"] == 1
    assert m["bubble_frac"] == pytest.approx((2 * 4 - 2) / (8 + 2 * 4 - 2))
    # the per-stage trapezoid: stage s fills s ticks at the start, and —
    # because the 1F1B family ENDS with the backward ripple toward stage
    # 0 — also finishes s ticks early (last backward of stage s lands at
    # tick T-1-s): stage 0 is busy until the final tick
    assert m["fill_ticks"] == [0, 1, 2, 3]
    assert m["drain_ticks"] == [0, 1, 2, 3]
    # gpipe is a forward program: stage s finishes P-1-s ticks early
    assert schedule_meta("gpipe", 4, 8)["drain_ticks"] == [3, 2, 1, 0]


def test_schedule_meta_interleaved_cuts_the_bubble():
    plain = schedule_meta("1f1b", pipe=4, microbatches=8)
    inter = schedule_meta("interleaved", pipe=4, microbatches=8, virtual=2)
    # v=2: ticks = M·v + v·P + P - 2, useful = M·v
    assert inter["ticks"] == 8 * 2 + 2 * 4 + 4 - 2
    assert inter["useful_ticks"] == 16
    # the tentpole claim, in schedule arithmetic: interleaving shrinks the
    # bubble FRACTION at fixed (P, M) — per-tick work also shrinks ~v×,
    # so the bubble TIME shrinks even further
    assert inter["bubble_frac"] < plain["bubble_frac"]
    deeper = schedule_meta("interleaved", pipe=4, microbatches=8, virtual=4)
    assert deeper["bubble_frac"] < inter["bubble_frac"]


def test_schedule_meta_gpipe_and_unknown():
    g = schedule_meta("gpipe", pipe=4, microbatches=12)
    assert g["ticks"] == 12 + 3 and g["useful_ticks"] == 12
    # gpipe ignores virtual (single contiguous slice per stage)
    assert schedule_meta("gpipe", 4, 12, virtual=3)["virtual"] == 1
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        schedule_meta("zigzag", 4, 12)


# -------------------------------------------------- actionable refusals


def test_microbatch_help_names_legal_counts():
    msg = microbatch_help(64, 5, 2)
    assert "64" in msg and "legal microbatch counts" in msg
    # legal m: 64 % (m*2) == 0 → ..., 8, 16, 32
    assert "32" in msg
    inter = microbatch_help(64, 6, 2, pipe=4)
    assert "multiple of the stage count 4" in inter


def test_pipeline_help_names_legal_degrees():
    msg = pipeline_help(8, 3, 2)
    assert "depth 8" in msg and "virtual=2" in msg
    # legal P at v=2: depth % (P*2) == 0 → 1, 2, 4
    assert "[1, 2, 4]" in msg


def test_validate_reshard_refuses_illegal_pipe_axis():
    class FakeMesh:
        shape = {"data": 2, "model": 1, "pipe": 3}

    with pytest.raises(ReshardError, match="legal --pipeline-parallel"):
        validate_reshard(
            {"mesh": {"data": 4, "model": 1, "pipe": 2}},
            FakeMesh(),
            batch_size=64,
            pipeline={"pipe": 3, "virtual": 1, "microbatches": 4, "depth": 8},
        )


def test_validate_reshard_refuses_indivisible_microbatches():
    class FakeMesh:
        shape = {"data": 4, "model": 1, "pipe": 2}

    with pytest.raises(ReshardError, match="legal microbatch counts"):
        validate_reshard(
            None,
            FakeMesh(),
            batch_size=64,
            pipeline={"pipe": 2, "virtual": 1, "microbatches": 6, "depth": 8},
        )


def test_validate_reshard_records_pipe_delta():
    class FakeMesh:
        shape = {"data": 2, "model": 1, "pipe": 2}

    plan = validate_reshard(
        {"mesh": {"data": 4, "model": 1, "pipe": 4}, "devices": 16},
        FakeMesh(),
        batch_size=64,
        pipeline={"pipe": 2, "virtual": 2, "microbatches": 4, "depth": 8},
    )
    assert plan["changed"]
    assert plan["saved_pipe"] == 4 and plan["pipe"] == 2
    assert plan["pipe_changed"]


def test_config_rejects_bad_pipeline_combos(tmp_path):
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--pipeline-parallel", "0"])
    with pytest.raises(SystemExit):
        load_config(
            "tpu",
            argv=["--pipeline-virtual-stages", "2"],  # needs interleaved
        )
    with pytest.raises(SystemExit):
        load_config(
            "tpu",
            argv=["--pipeline-parallel", "2", "--parallel-style", "pipeline"],
        )
    hp = load_config(
        "tpu",
        argv=[
            "--pipeline-parallel", "2", "--pipeline-schedule", "interleaved",
            "--pipeline-virtual-stages", "2",
        ],
    )
    assert hp.pipeline_parallel == 2 and hp.pipeline_virtual_stages == 2


# ------------------------------------------- per-stage desync fingerprints


def test_check_partial_desync_cube_names_the_stage():
    # (data=2, model=2, pipe=3) cube: in-sync everywhere except stage 2
    cube = np.ones((2, 2, 3), np.float64)
    cube[1, 0, 2] += 0.25
    report = check_partial_desync(cube)
    assert report["mismatch"]
    assert report["per_stage_spread"] == [0.0, 0.0, 0.25]
    clean = check_partial_desync(np.ones((2, 2, 3)))
    assert not clean["mismatch"]
    assert clean.get("per_stage_spread", [0, 0, 0]) == [0.0, 0.0, 0.0]


def test_check_partial_desync_2d_matrix_unchanged():
    m = np.ones((4, 2))
    m[3, 1] += 0.5
    report = check_partial_desync(m)
    assert report["mismatch"] and "per_stage_spread" not in report
    assert report["per_model_spread"] == [0.0, 0.5]


# ------------------------------------------- per-stage straggler sketches


def _metrics_event(proc, metrics, attempt=0):
    return {
        "v": 1, "run_id": "r", "attempt": attempt, "process_index": proc,
        "t_wall": 1.0, "t_mono": 1.0, "kind": "metrics",
        "payload": {"metrics": metrics},
    }


def _hist(samples):
    """A sketch snapshot in the merge format (obs/metrics.py)."""
    from distributed_training_comparison_tpu.obs.metrics import Histogram

    h = Histogram("test")
    for s in samples:
        h.record(s)
    return h.snapshot()


def test_straggler_findings_gain_stage_dimension():
    # two hosts each owning one pipeline stage; host 1's stage sketch is
    # 10x slower — the finding must name phase stage1 AND carry stage=1
    fast = _hist([0.1] * 8)
    slow = _hist([1.0] * 8)
    events = [
        _metrics_event(0, {"step/stage0/busy_s": fast}),
        _metrics_event(1, {"step/stage1/busy_s": fast}),
    ] * 2 + [
        _metrics_event(0, {"step/stage0/busy_s": fast}),
        _metrics_event(1, {"step/stage1/busy_s": slow}),
    ]
    # cross-host comparison happens per phase; put both hosts on BOTH
    # stage phases so the leave-one-out baseline exists
    events += [
        _metrics_event(0, {"step/stage1/busy_s": fast}),
        _metrics_event(1, {"step/stage0/busy_s": fast}),
    ]
    findings = straggler.straggler_findings(events, threshold_mads=3.0)
    stage_findings = [f for f in findings if f["phase"].startswith("stage")]
    assert stage_findings, "no stage-phase finding produced"
    worst = stage_findings[0]
    assert worst["process_index"] == 1
    assert worst["phase"] == "stage1"
    assert worst["stage"] == 1
    # the table renders the stage columns and marks the straggler
    lines = straggler.format_table(events)
    assert any("stage1" in line for line in lines)
    assert any("pipeline stage 1" in line for line in lines)


def test_straggler_plain_phases_unchanged():
    fast = _hist([0.1] * 8)
    slow = _hist([2.0] * 8)
    events = [
        _metrics_event(0, {"step/dispatch_s": fast}),
        _metrics_event(1, {"step/dispatch_s": slow}),
    ]
    findings = straggler.straggler_findings(events, threshold_mads=3.0)
    assert findings and findings[0]["phase"] == "dispatch"
    assert "stage" not in findings[0]


# --------------------------------------------- run_report bubble table


def _pipeline_event(**payload):
    base = dict(
        schedule="interleaved", pipe=2, virtual=2, microbatches=4,
        tp=2, data=2, ticks=14, useful_ticks=8, bubble_frac=0.4286,
        depth=8,
    )
    base.update(payload)
    return {
        "v": 1, "run_id": "r", "attempt": 0, "process_index": 0,
        "t_wall": 1.0, "t_mono": 1.0, "kind": "pipeline", "payload": base,
    }


def _compile_event(name, fp):
    return {
        "v": 1, "run_id": "r", "attempt": 0, "process_index": 0,
        "t_wall": 1.0, "t_mono": 1.0, "kind": "compile",
        "payload": {
            "name": name, "fingerprint": fp, "compile_s": 0.5,
            "cache": "miss", "flops": 1e9,
        },
    }


def test_run_report_pipeline_bubble_table():
    disp = _hist([0.5] * 4)
    events = [
        _pipeline_event(),
        _compile_event("device_chunk_runner@k2", "abcd1234"),
        _compile_event("eval_runner", "ffff0000"),
        _metrics_event(
            0, {"exec/device_chunk_runner@k2:abcd1234/dispatch_s": disp}
        ),
    ]
    comp = run_report.compute_summary(events)
    pipe = comp["pipeline"]
    assert pipe["meta"]["schedule"] == "interleaved"
    rows = pipe["rows"]
    assert len(rows) == 1  # eval_runner carries no bubble
    row = rows[0]
    assert row["name"] == "device_chunk_runner@k2"
    assert row["bubble_frac"] == pytest.approx(0.4286)
    assert row["bubble_s"] == pytest.approx(2.0 * 0.4286, rel=1e-3)
    text = run_report.format_compute(comp)
    assert "bubble" in text and "interleaved" in text
    # the summary path renders the same section
    summary = run_report.format_summary("x", run_report.summarize(events))
    assert "schedule=interleaved" in summary


def test_run_report_without_pipeline_event_unchanged():
    events = [_compile_event("device_chunk_runner@k2", "abcd1234")]
    comp = run_report.compute_summary(events)
    assert "pipeline" not in comp


# --------------------------------------------- synthetic (host,stage) lanes


def test_span_recorder_record_makes_stage_lanes():
    rec = obs.SpanRecorder(process_index=0)
    rec.record("pp_busy", 1.0, 2.0, lane="stage0", stage=0, bubble_frac=0.3)
    rec.record("pp_fill_bubble", 1.0, 1.2, lane="stage1", stage=1)
    with rec.span("epoch"):  # a real thread span coexists
        pass
    trace = obs.chrome_trace(rec.spans(), 0)
    names = {
        (e.get("args") or {}).get("name")
        for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert {"stage0", "stage1"} <= names
    busy = next(
        e for e in trace["traceEvents"] if e.get("name") == "pp_busy"
    )
    assert busy["dur"] == pytest.approx(1e6)  # µs
    assert busy["args"]["bubble_frac"] == 0.3
    # the two lanes get distinct stable pseudo thread ids
    tids = {
        e["tid"]
        for e in trace["traceEvents"]
        if e.get("name", "").startswith("pp_")
    }
    assert len(tids) == 2


def test_pipeline_event_kind_registered_and_accepted():
    assert "pipeline" in obs.KNOWN_KINDS
