"""Config-layer tests: reference flag surface is preserved, TPU extras work."""

from distributed_training_comparison_tpu.config import load_config


def test_reference_defaults():
    cfg = load_config("single", argv=[])
    # reference src/single/config.py defaults
    assert cfg.dset == "cifar100"
    assert cfg.dpath == "data/"
    assert cfg.seed == 42
    assert cfg.eval_step == 300
    assert cfg.amp is False
    assert cfg.contain_test is False
    assert cfg.batch_size == 128
    assert cfg.lr == 0.1
    assert cfg.weight_decay == 0.0001
    assert cfg.lr_decay_gamma == 0.1
    assert cfg.model == "resnet18"


def test_reference_launcher_flags_parse():
    # the exact flag set used by reference run_single.sh:13-22
    cfg = load_config(
        "single",
        argv=[
            "--seed=42",
            "--epoch=50",
            "--batch-size=128",
            "--lr=0.1",
            "--weight-decay=0.0001",
            "--lr-decay-step-size=25",
            "--lr-decay-gamma=0.1",
            "--amp",
            "--contain-test",
        ],
    )
    assert cfg.epoch == 50
    assert cfg.lr_decay_step_size == 25
    assert cfg.amp and cfg.contain_test
    assert cfg.precision == "bf16"  # --amp maps to bf16 policy


def test_epoch_default_per_backend():
    # reference: single defaults to 200 epochs, dp/ddp to 100
    # (src/single/config.py:21 vs src/ddp/config.py:29)
    assert load_config("single", argv=[]).epoch == 200
    assert load_config("dp", argv=[]).epoch == 100
    assert load_config("ddp", argv=[]).epoch == 100
    assert load_config("tpu", argv=[]).epoch == 100


def test_ddp_flags_parse():
    cfg = load_config(
        "ddp",
        argv=["--world-size=4", "--rank=1", "--dist-url=10.0.0.1:1234"],
    )
    assert cfg.world_size == 4 and cfg.rank == 1
    assert cfg.backend == "ddp"
    assert "checkpoints" in cfg.ckpt_path and "ddp" in cfg.ckpt_path


def test_precision_override():
    cfg = load_config("single", argv=["--amp", "--precision", "fp32"])
    assert cfg.precision == "fp32"
