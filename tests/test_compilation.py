"""Compiler & memory observability (ISSUE 8): compile events with the
HLO cost/memory ledger, the recompilation sentinel, measured per-
executable MFU in run_report --compute, and the satellites that ride
along (livelock-aware "stuck" stall classification, fleet-aggregate
alert rules, compile-tainted straggler-sample exclusion, the
jax.live_arrays census).

The load-bearing properties pinned here:

- every distinct executable an instrumented function builds emits ONE
  schema-valid ``compile`` event with a fingerprint that is a pure
  function of (family, abstract shapes/dtypes/shardings) — identical
  across processes, distinct across signatures;
- the persistent compile cache's hit/miss outcome is distinguished, and
  a jax without the analysis APIs degrades to events without flops —
  never to a crash in the training path;
- the sentinel flags exactly the compiles that happen after ``warm()``
  on sentinel-tracked families — the serve-bucket-miss e2e drives a
  real ``--alert`` rule through firing and resolved;
- ``run_report --compute`` reconstructs the per-executable table
  (compiles, cache, compile time, flops, peak HBM, measured MFU) from
  the event stream alone.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))

import run_report  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.obs import compilation as compilation_mod
from distributed_training_comparison_tpu.obs.alerts import (
    AlertEngine,
    AlertRule,
    AlertSpecError,
)
from distributed_training_comparison_tpu.obs.heartbeat import LivenessTracker
from distributed_training_comparison_tpu.obs.metrics import (
    MetricRegistry,
    merge_metric_events,
)
from distributed_training_comparison_tpu.obs.resource import (
    ResourceSampler,
    live_array_census,
)
from distributed_training_comparison_tpu.obs.straggler import (
    straggler_findings,
)
from distributed_training_comparison_tpu.utils import StepTimeMeter

pytestmark = pytest.mark.obs


@pytest.fixture
def monitor_env():
    """A live bus + registry + monitor, torn down afterwards so the
    process-current bus never leaks between tests."""
    bus = obs.configure(run_id=obs.new_run_id(), persist=True)
    registry = MetricRegistry(flush_steps=1)
    monitor = obs.CompileMonitor(bus=bus, registry=registry)
    yield bus, registry, monitor
    obs.reset()


def _compile_events(bus):
    return [e for e in bus.ring_events() if e["kind"] == "compile"]


# ------------------------------------------------------- events + schema


def test_compile_event_schema_and_dedup(monitor_env):
    bus, registry, monitor = monitor_env
    fn = monitor.instrument(jax.jit(lambda x: (x * 2.0).sum()), "double")
    x = np.ones((8, 8), np.float32)
    assert float(fn(x)) == 128.0
    assert float(fn(x)) == 128.0  # same signature: no second compile
    events = _compile_events(bus)
    assert len(events) == 1
    ev = events[0]
    assert obs.validate_event(ev) == []
    p = ev["payload"]
    assert p["name"] == "double"
    assert len(p["fingerprint"]) == 16
    assert p["compile_s"] > 0
    assert p["cache"] in ("hit", "miss", "off", "unknown")
    assert p["compiles_of_fingerprint"] == 1
    assert p["recompile_after_warmup"] is False
    # device identity comes from the EXECUTABLE's own device set (a
    # plain unsharded jit compiles for one device, not the 8-device
    # default backend) — the honest MFU denominator
    assert p["platform"] == "cpu" and p["devices"] == 1
    # this jax HAS the analyses: the ledger numbers must be present
    assert p["flops"] > 0
    assert p["peak_bytes"] > 0 and p["argument_bytes"] > 0
    # a new signature is a new executable with a distinct fingerprint
    fn(np.ones((4, 4), np.float32))
    events = _compile_events(bus)
    assert len(events) == 2
    assert events[1]["payload"]["fingerprint"] != p["fingerprint"]


def test_compile_metrics_ride_the_registry(monitor_env):
    bus, registry, monitor = monitor_env
    fn = monitor.instrument(jax.jit(lambda x: x + 1), "bump")
    fn(np.zeros(4, np.float32))
    fn(np.zeros(4, np.float32))
    fn(np.zeros(8, np.float32))
    snaps = registry.snapshot(reset=False)
    assert snaps["compile/total"]["n"] == 2
    assert snaps["compile/by/bump"]["n"] == 2
    assert snaps["compile/time_s"]["count"] == 2
    assert snaps["compile/executables"]["value"] == 2.0
    assert snaps["compile/peak_hbm_bytes"]["value"] > 0
    # per-executable dispatch sketches: count == dispatches through each
    dispatch = {
        k: v["count"] for k, v in snaps.items()
        if k.startswith("exec/bump:")
    }
    assert sorted(dispatch.values()) == [1, 2]


def test_fingerprint_stable_across_processes():
    """Two fresh interpreters describing the same (family, abstract args,
    sharding, mesh) must produce the SAME fingerprint — the cross-host
    join key --compute relies on — and a different shape a different
    one.  Child processes inherit the 8-device XLA_FLAGS from conftest's
    module-scope environ write."""
    script = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from distributed_training_comparison_tpu import obs\n"
        "from distributed_training_comparison_tpu.parallel import make_mesh\n"
        "from distributed_training_comparison_tpu.parallel.sharding import"
        " put_replicated\n"
        "mesh = make_mesh(0, 1, backend='cpu')\n"
        "x = put_replicated(np.ones((16, 4), np.float32), mesh)\n"
        "y = np.ones((3,), np.int32)\n"
        "print(obs.signature_fingerprint('fam', (x, y)))\n"
        "print(obs.signature_fingerprint('fam', (x,)))\n"
    )
    outs = [
        subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, check=True,
            cwd=str(Path(__file__).parent.parent),
        ).stdout.split()
        for _ in range(2)
    ]
    assert outs[0] == outs[1]
    assert outs[0][0] != outs[0][1]  # different args, different executable


def test_persistent_cache_hit_and_miss_distinguished(tmp_path, monitor_env):
    bus, registry, monitor = monitor_env
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        x = np.ones((16, 16), np.float32)
        fn1 = monitor.instrument(jax.jit(lambda a: a @ a), "mm")
        fn1(x)
        # a FRESH jit of the same program: the AOT compile must be served
        # by the on-disk cache this time
        fn2 = monitor.instrument(jax.jit(lambda a: a @ a), "mm")
        fn2(x)
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", old_min
        )
    first, second = [e["payload"]["cache"] for e in _compile_events(bus)]
    assert first == "miss"
    assert second == "hit"
    snaps = registry.snapshot(reset=False)
    assert snaps["compile/persistent_cache_misses"]["n"] == 1
    assert snaps["compile/persistent_cache_hits"]["n"] == 1


def test_absent_analysis_apis_degrade_to_no_data(monitor_env, monkeypatch):
    """A jax that dropped cost_analysis/memory_analysis yields compile
    events without flops/bytes — never an exception in the train path."""
    bus, registry, monitor = monitor_env
    monkeypatch.setattr(
        compilation_mod, "executable_cost_analysis", lambda c: None
    )
    monkeypatch.setattr(
        compilation_mod, "executable_memory_analysis", lambda c: None
    )
    fn = monitor.instrument(jax.jit(lambda x: x * 3.0), "noapi")
    out = fn(np.ones(4, np.float32))
    assert float(out.sum()) == 12.0
    (ev,) = _compile_events(bus)
    p = ev["payload"]
    assert "flops" not in p and "peak_bytes" not in p
    assert obs.validate_event(ev) == []
    assert monitor.ledger()[0]["flops"] is None


def test_broken_lowering_falls_back_to_plain_jit(monitor_env):
    bus, registry, monitor = monitor_env
    jitted = jax.jit(lambda x: x - 1)

    class NoLower:
        def __call__(self, *args):
            return jitted(*args)

        def lower(self, *args):  # simulate AOT API drift
            raise AttributeError("lower moved")

    fn = monitor.instrument(NoLower(), "drifted")
    out = fn(np.ones(4, np.float32))
    assert float(out.sum()) == 0.0
    assert fn(np.ones(4, np.float32)) is not None  # cached fallback path
    assert _compile_events(bus) == []  # unobserved, but unharmed


def test_disabled_monitor_is_a_passthrough():
    monitor = obs.CompileMonitor(enabled=False)
    jitted = jax.jit(lambda x: x)
    assert monitor.instrument(jitted, "x") is jitted
    compiled, rec = monitor.aot_compile(
        "y", lambda: jax.jit(lambda a: a).lower(np.zeros(2)).compile(),
        parts=("p",),
    )
    assert rec is None and compiled is not None
    assert monitor.take_taint() is False


# ------------------------------------------------- recompilation sentinel


def test_sentinel_flags_only_post_warm_compiles(monitor_env):
    bus, registry, monitor = monitor_env
    fn = monitor.instrument(jax.jit(lambda x: x * 2), "hot")
    cold = monitor.instrument(
        jax.jit(lambda x: x * 4), "evalish", sentinel=False
    )
    fn(np.zeros(4, np.float32))  # pre-warm: not flagged
    monitor.warm()
    fn(np.zeros(8, np.float32))  # post-warm sentinel family: flagged
    cold(np.zeros(2, np.float32))  # post-warm but sentinel=False: not
    flags = [
        e["payload"]["recompile_after_warmup"] for e in _compile_events(bus)
    ]
    assert flags == [False, True, False]
    snaps = registry.snapshot(reset=False)
    assert snaps["compile/recompiles_after_warmup"]["n"] == 1


def test_serve_bucket_miss_trips_sentinel_and_alert_rule(monitor_env):
    """ISSUE 8 acceptance: a forced serve bucket miss — traffic landing
    on a bucket the replica never warmed — drives the sentinel metric,
    and an --alert rule on it fires, then resolves on the next clean
    window."""
    from distributed_training_comparison_tpu.serve import ServeEngine

    bus, registry, monitor = monitor_env
    engine = AlertEngine(
        [AlertRule.parse("compile/recompiles_after_warmup:n>0")], bus=bus
    )
    bus.subscribe(engine.observe_event)
    try:
        serve = ServeEngine(
            model_name="resnet18", buckets=(1, 2, 8), precision="fp32",
            monitor=monitor,
        )
        serve.warmup(buckets=(1, 2))  # the replica's expected traffic
        assert monitor.is_warm
        registry.flush(bus)
        assert not engine.firing  # warmup compiles are not findings
        # the flash crowd: 5 rows → bucket 8, never compiled → sentinel
        serve.predict_logits(np.zeros((5, 32, 32, 3), np.uint8))
        registry.flush(bus)
        assert engine.firing
        registry.flush(bus)  # next window is clean: counter delta == 0
        assert not engine.firing
    finally:
        bus.unsubscribe(engine.observe_event)
    states = [
        e["payload"]["state"] for e in bus.ring_events()
        if e["kind"] == "alert"
    ]
    assert states == ["firing", "resolved"]
    ledger = {r["fingerprint"]: r for r in monitor.ledger()}
    assert sum(r["recompile_after_warmup"] for r in ledger.values()) == 1


def test_warmup_rejects_bucket_outside_ladder():
    from distributed_training_comparison_tpu.serve import ServeEngine

    serve = ServeEngine(model_name="resnet18", buckets=(1, 2), precision="fp32")
    with pytest.raises(ValueError, match="not in the ladder"):
        serve.warmup(buckets=(4,))


# -------------------------- satellite: compile-tainted sample exclusion


def test_meter_routes_compile_bearing_samples_separately():
    registry = MetricRegistry()
    flag = {"v": False}

    def taint():
        v, flag["v"] = flag["v"], False
        return v

    meter = StepTimeMeter(metrics=registry)
    with meter.phase("dispatch", taint=taint):
        flag["v"] = True  # a compile happened inside this span
    with meter.phase("dispatch", taint=taint):
        pass
    # stale taint raised OUTSIDE any phase must NOT poison the next one
    flag["v"] = True
    with meter.phase("dispatch", taint=taint):
        pass
    snaps = registry.snapshot(reset=False)
    assert snaps["step/dispatch_compile_s"]["count"] == 1
    assert snaps["step/dispatch_s"]["count"] == 2
    # the wall clock still counts into the epoch totals either way
    assert meter.seconds["dispatch"] >= 0


def test_straggler_scoring_ignores_compile_tainted_sketches():
    """A host whose only outlier samples live in the compile-tainted
    sketch must produce NO finding — the clean series is the yardstick."""
    def flush(proc, name, values):
        h = MetricRegistry()
        for v in values:
            h.histogram(name).record(v)
        return {
            "v": 1, "run_id": "r", "attempt": 0, "process_index": proc,
            "t_wall": 0.0, "t_mono": 0.0, "kind": "metrics",
            "payload": {"metrics": h.snapshot(reset=False)},
        }

    events = []
    for proc in (0, 1, 2):
        events.append(flush(proc, "step/dispatch_s", [0.1] * 10))
    # host 1's compile cliff lands ONLY in the tainted sketch
    events.append(flush(1, "step/dispatch_compile_s", [30.0] * 10))
    assert straggler_findings(events) == []


# ------------------------- satellite: livelock-aware "stuck" stall state


def test_liveness_tracker_flags_stuck_then_recovered():
    tracker = LivenessTracker(heartbeat_s=10.0, stuck_after_beats=3)

    def beat(t, step):
        tracker.observe(
            {"kind": "heartbeat", "process_index": 0, "attempt": 0,
             "step": step, "epoch": 0},
            now=t,
        )

    t = 0.0
    for i in range(3):
        beat(t, step=10 + i)  # advancing: healthy
        t += 10.0
        assert tracker.check(now=t) == []
    for _ in range(3):  # beats keep arriving, step frozen
        beat(t, step=13)
        t += 10.0
    findings = tracker.check(now=t)
    assert [f["state"] for f in findings] == ["stuck"]
    assert tracker.check(now=t) == []  # no flap while it persists
    beat(t, step=14)  # progress resumes
    findings = tracker.check(now=t + 1.0)
    assert [f["state"] for f in findings] == ["recovered"]


def test_stuck_yields_to_age_based_states_when_beats_stop():
    tracker = LivenessTracker(heartbeat_s=1.0, stuck_after_beats=2)
    for i in range(3):  # stuck at step 5, beating on schedule
        tracker.observe(
            {"kind": "heartbeat", "process_index": 0, "step": 5}, now=float(i)
        )
    assert [f["state"] for f in tracker.check(now=3.0)] == ["stuck"]
    # then the beats stop entirely: silence escalates past livelock
    assert [f["state"] for f in tracker.check(now=30.0)] == ["dead"]


# ------------------------- satellite: fleet-aggregate alert rules


def test_fleet_aggregate_rule_parses_and_requires_fleet_engine():
    rule = AlertRule.parse("sum(train/skipped_steps):n>3")
    assert rule.fleet_agg == "sum" and rule.metric == "train/skipped_steps"
    assert AlertRule.parse("max(res/host_rss_bytes):value>1e9").fleet_agg == "max"
    with pytest.raises(AlertSpecError):
        AlertRule.parse("sum(heartbeat):age>30")
    with pytest.raises(AlertSpecError):
        AlertRule.parse("avg(x/y):n>1")

    def flush(proc, n):
        return {
            "kind": "metrics", "process_index": proc,
            "payload": {"metrics": {
                "train/skipped_steps": {"type": "counter", "n": n}
            }},
        }

    fleet = AlertEngine([AlertRule.parse("sum(train/skipped_steps):n>3")],
                        fleet=True)
    fleet.observe_event(flush(0, 2))
    assert not fleet.firing  # one host's 2 is under the fleet threshold
    fleet.observe_event(flush(1, 2))
    # both hosts folded, but the aggregate is evaluated once per flush
    # ROUND (N staggered flushes of one window must advance a for=N rule
    # by one, not N) — the round closes when a host reports again
    assert not fleet.firing
    fleet.observe_event(flush(0, 2))
    assert fleet.firing  # round closed: 2 + 2 crosses the threshold
    assert fleet.transitions[0]["source"] == "fleet"

    local = AlertEngine([AlertRule.parse("sum(train/skipped_steps):n>3")],
                        fleet=False)
    for _ in range(3):
        local.observe_event(flush(0, 100))
    assert not local.firing  # in-process engines must skip fleet rules


def test_fleet_for_n_counts_rounds_not_process_flushes():
    """for=3 on a fleet rule: one breaching window flushed by 8 hosts
    must count as ONE window, not fire instantly."""
    rule = AlertRule.parse("sum(train/skipped_steps):n>0:for=3")
    engine = AlertEngine([rule], fleet=True)

    def flush(proc, n):
        return {
            "kind": "metrics", "process_index": proc,
            "payload": {"metrics": {
                "train/skipped_steps": {"type": "counter", "n": n}
            }},
        }

    for rnd in range(3):
        assert not engine.firing, f"fired after only {rnd} round(s)"
        for proc in range(8):
            engine.observe_event(flush(proc, 1))
    engine.observe_event(flush(0, 1))  # closes the third breaching round
    assert engine.firing


def test_fleet_max_aggregate_drops_dead_hosts_and_resolves():
    rule = AlertRule.parse("max(res/open_fds):value>100:for=1")
    engine = AlertEngine([rule], fleet=True)

    def flush(proc, v):
        return {
            "kind": "metrics", "process_index": proc,
            "payload": {"metrics": {"res/open_fds": {"type": "gauge", "value": v}}},
        }

    engine.observe_event(flush(0, 50))
    engine.observe_event(flush(1, 150))
    engine.observe_event(flush(0, 50))  # round closes: max(50, 150)
    assert engine.firing
    # host 1 dies (never reports again): its stale 150 must fall out of
    # the fold at the next round, so the rule can resolve
    engine.observe_event(flush(0, 50))
    assert not engine.firing
    # attempt reset forgets the fold entirely, hysteresis state survives
    engine.reset_fleet()
    assert engine._fleet_state == {}


# ------------------------- satellite: live-array census


def test_live_array_census_counts_and_skips_deleted():
    keep = jnp.ones((128,), jnp.float32)
    census = live_array_census()
    assert census is not None
    count, total = census
    assert count >= 1 and total >= keep.nbytes
    dead = jnp.ones((64,), jnp.float32)
    dead.delete()
    count2, total2 = live_array_census()  # deleted arrays never raise out
    assert count2 >= 1
    registry = MetricRegistry()
    sampler = ResourceSampler(min_interval_s=0.0)
    values = sampler.sample(registry)
    assert values.get("res/live_arrays", 0) >= 1
    assert values.get("res/live_array_bytes", 0) > 0


# ----------------------------------------- run_report --compute offline


def _compile_event(name, fp, **payload):
    base = {
        "v": 1, "run_id": "r", "attempt": 0, "process_index": 0,
        "t_wall": 1.0, "t_mono": 1.0, "kind": "compile",
        "payload": {
            "name": name, "fingerprint": fp, "compile_s": 0.5,
            "cache": "miss", "compiles_of_fingerprint": 1,
            "recompile_after_warmup": False, "platform": "tpu",
            "device_kind": "TPU v4", "devices": 4, "flops": 1e12,
            "peak_bytes": 2 << 30, **payload,
        },
    }
    return base


def _exec_flush(name, fp, count, total_s):
    reg = MetricRegistry()
    h = reg.histogram(f"exec/{name}:{fp[:8]}/dispatch_s")
    for _ in range(count):
        h.record(total_s / count)
    return {
        "v": 1, "run_id": "r", "attempt": 0, "process_index": 0,
        "t_wall": 2.0, "t_mono": 2.0, "kind": "metrics",
        "payload": {"metrics": reg.snapshot(reset=False)},
    }


def test_compute_summary_measured_mfu_from_events_alone():
    fp = "aabbccddeeff0011"
    events = [
        _compile_event("chunk_runner", fp),
        _exec_flush("chunk_runner", fp, count=10, total_s=10.0),
    ]
    comp = run_report.compute_summary(events)
    (row,) = comp["rows"]
    assert row["compiles"] == 1 and row["cache_misses"] == 1
    assert row["dispatches"] == 10
    assert abs(row["dispatch_s"] - 10.0) < 0.2  # sketch-quantized sum
    # 1e12 flops x 10 dispatches / 10 s / (275e12 x 4 chips) ≈ 0.0909%
    assert row["mfu"] == pytest.approx(
        1e12 * 10 / row["dispatch_s"] / (275e12 * 4), rel=1e-6
    )
    text = run_report.format_compute(comp)
    assert "chunk_runner" in text and "aabbccdd" in text
    assert "measured MFU" in text
    # --peak-flops overrides the device-kind table
    comp2 = run_report.compute_summary(events, peak_override=1e12)
    assert comp2["rows"][0]["mfu"] == pytest.approx(
        1e12 * 10 / comp2["rows"][0]["dispatch_s"] / (1e12 * 4), rel=1e-6
    )


def test_compute_summary_marks_sentinel_findings_and_unknown_peak():
    events = [
        _compile_event(
            "serve_predict", "0123456789abcdef",
            recompile_after_warmup=True, device_kind="cpu", platform="cpu",
        ),
    ]
    comp = run_report.compute_summary(events)
    assert comp["totals"]["recompiles_after_warmup"] == 1
    assert comp["rows"][0]["mfu"] is None  # no peak entry for cpu
    text = run_report.format_compute(comp)
    assert "AFTER warmup" in text


def test_check_require_kind(tmp_path):
    path = tmp_path / "events.jsonl"
    ev = {
        "v": 1, "run_id": "r", "attempt": 0, "process_index": 0,
        "t_wall": 1.0, "t_mono": 1.0, "kind": "run_start",
    }
    path.write_text(json.dumps(ev) + "\n")
    assert run_report.check_run(tmp_path) == []
    problems = run_report.check_run(tmp_path, require_kinds=("compile",))
    assert problems and "compile" in problems[0]
    assert run_report.main([str(tmp_path), "--check"]) == 0
    assert run_report.main(
        [str(tmp_path), "--check", "--require-kind", "compile"]
    ) == 1


# ------------------------------------------------------- trainer + e2e


def _tiny_trainer(tmp_path, extra=()):
    from test_train import TinyNet  # noqa: E402

    from distributed_training_comparison_tpu.train import Trainer

    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "640",
            "--batch-size", "32", "--epoch", "2", "--no-progress",
            "--eval-step", "10000", "--seed", "7",
            "--save-last-min-secs", "0", "--device-chunk-steps", "6",
            "--metrics-flush-steps", "8", "--ckpt-path", str(tmp_path),
            *extra,
        ],
    )
    return Trainer(hp, model=TinyNet(num_classes=100))


def test_trainer_emits_compile_events_and_compute_table(tmp_path, capsys):
    """A real (in-process) training run produces `compile` events for
    every distinct executable, and run_report --compute renders the
    per-executable table — dispatch counts, cache column, flops, peak
    HBM, measured MFU (forced via --peak-flops on this CPU host) — from
    the event stream alone."""
    trainer = _tiny_trainer(tmp_path)
    try:
        trainer.fit()
        trainer.test()
    finally:
        trainer.close()
    events, _files = run_report.load_run(tmp_path)
    comp_events = [e for e in events if e.get("kind") == "compile"]
    names = {e["payload"]["name"] for e in comp_events}
    assert any(n.startswith("device_chunk_runner") for n in names)
    assert "eval_runner" in names
    for ev in comp_events:
        assert obs.validate_event(ev) == []
    comp = run_report.compute_summary(events, peak_override=1e12)
    by_name = {r["name"]: r for r in comp["rows"]}
    chunk = next(
        r for n, r in by_name.items() if n.startswith("device_chunk_runner")
    )
    assert chunk["compiles"] == 1
    assert chunk["cache"] in ("hit", "miss")
    assert chunk["dispatches"] >= 2  # 2 epochs x >=1 full chunk each
    assert chunk["flops"] > 0 and chunk["peak_bytes"] > 0
    assert chunk["mfu"] is not None and chunk["mfu"] > 0
    # no sentinel findings in an undisturbed run: steady state is steady
    assert comp["totals"]["recompiles_after_warmup"] == 0
    # the CLI path renders the same table
    rc = run_report.main([str(tmp_path), "--compute", "--peak-flops", "1e12"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "device_chunk_runner" in out and "measured MFU" in out
    # and the capture passes the kind-required self check
    assert run_report.main(
        [str(tmp_path), "--check", "--require-kind", "compile"]
    ) == 0


def test_no_obs_run_emits_no_compile_events(tmp_path):
    trainer = _tiny_trainer(tmp_path, extra=("--no-obs", "--no-flight-ring"))
    try:
        trainer.fit()
    finally:
        trainer.close()
    assert not list(Path(tmp_path).glob("version-*/events*.jsonl"))
    assert trainer.compile_monitor.ledger() == []


@pytest.mark.slow
def test_e2e_supervised_run_compile_ledger(tmp_path):
    """ISSUE 8 acceptance (supervised leg): a supervised CPU run through
    a preemption produces `compile` events in EVERY attempt, the
    --compute table reconstructs per-executable rows with measured MFU
    from the merged stream, --diff carries the compiler rows, and no
    false sentinel finding appears (each attempt re-warms its own
    monitor)."""
    from distributed_training_comparison_tpu.resilience import Supervisor

    worker = Path(__file__).parent / "resil_worker.py"
    run_id = obs.new_run_id()

    def env_for(attempt):
        import os

        env = dict(os.environ)
        env[obs.RUN_ID_ENV] = run_id
        env[obs.ATTEMPT_ENV] = str(attempt)
        return env

    cmd = [
        sys.executable, str(worker),
        "--synthetic-data", "--limit-examples", "256",
        "--batch-size", "32", "--epoch", "3", "--no-progress",
        "--eval-step", "10000", "--save-last-min-secs", "0",
        "--device-chunk-steps", "4", "--metrics-flush-steps", "4",
        "--resilience", "--auto-resume",
        "--fault-plan", "preempt@epoch=1",
        "--ckpt-path", str(tmp_path),
    ]
    summary = Supervisor(cmd, env=env_for, max_restarts=3).run()
    assert summary["final_rc"] == 0 and summary["preemptions"] == 1

    events, _files = run_report.load_run(tmp_path)
    by_attempt = {}
    for ev in events:
        if ev.get("kind") == "compile":
            by_attempt.setdefault(int(ev.get("attempt", 0)), []).append(ev)
    assert set(by_attempt) == {0, 1}  # both attempts observed compiles
    assert all(
        not e["payload"]["recompile_after_warmup"]
        for evs in by_attempt.values() for e in evs
    )
    comp = run_report.compute_summary(events, peak_override=1e12)
    assert comp["totals"]["compiles"] >= 2
    chunk_rows = [
        r for r in comp["rows"] if r["name"].startswith("device_chunk_runner")
    ]
    assert chunk_rows and any(r["mfu"] for r in chunk_rows)
    # the self check the bench resilience leg now runs
    assert run_report.check_run(tmp_path, require_kinds=("compile",)) == []
    # --diff over the same run: the compiler rows render with zero delta
    diff = run_report.format_diff(
        "a", run_report.summarize(events), "b", run_report.summarize(events)
    )
    assert "compiles" in diff and "mfu %" in diff
