"""Model-zoo parity tests.

The reference only has a commented-out smoke test (``src/single/net.py:139-145``
builds ResNet18 and checks the output shape on a random 1×3×32×32 input).  Here
we verify, for every zoo entry:

- output shape (N, 100) on CIFAR-shaped NHWC input
- parameter-count parity with the reference architecture, via an *independent*
  analytic count derived from the block specs in SURVEY.md §2.1 #7 (and the
  known torch total for ResNet-18/CIFAR-100: 11,220,132)
- train-mode batch_stats mutation and eval-mode determinism
- bf16 compute policy yields float32 logits
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_comparison_tpu.models import get_model

WIDTHS = (64, 128, 256, 512)
STRIDES = (1, 2, 2, 2)
DEPTHS = {
    "resnet18": ("basic", (2, 2, 2, 2)),
    "resnet34": ("basic", (3, 4, 6, 3)),
    "resnet50": ("bottleneck", (3, 4, 6, 3)),
    "resnet101": ("bottleneck", (3, 4, 23, 3)),
    "resnet152": ("bottleneck", (3, 8, 36, 3)),
}


def analytic_param_count(kind: str, depths, num_classes=100) -> int:
    """Count learnable params of the reference architecture from first
    principles: conv k*k*cin*cout (no bias), BN scale+bias = 2c, linear
    cin*cout + cout.  Mirrors torch's .parameters() (running stats excluded).
    """
    exp = 1 if kind == "basic" else 4
    total = 3 * 3 * 3 * 64 + 2 * 64  # stem conv + stem bn
    cin = 64
    for planes, stride, blocks in zip(WIDTHS, STRIDES, depths):
        for i in range(blocks):
            s = stride if i == 0 else 1
            if kind == "basic":
                total += 3 * 3 * cin * planes + 2 * planes
                total += 3 * 3 * planes * planes + 2 * planes
            else:
                total += cin * planes + 2 * planes
                total += 3 * 3 * planes * planes + 2 * planes
                total += planes * (planes * exp) + 2 * planes * exp
            if s != 1 or cin != planes * exp:
                total += cin * planes * exp + 2 * planes * exp
            cin = planes * exp
    total += cin * num_classes + num_classes
    return total


def n_params(tree) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(tree))


@pytest.fixture(scope="module")
def rng():
    return jax.random.key(0)


# fast gate: one basic-block + one bottleneck representative
@pytest.mark.parametrize(
    "name",
    [
        n if n in ("resnet18", "resnet50")
        else pytest.param(n, marks=pytest.mark.slow)
        for n in DEPTHS
    ],
)
def test_shape_and_param_count(name, rng):
    model = get_model(name)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(rng, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 100)
    kind, depths = DEPTHS[name]
    assert n_params(variables["params"]) == analytic_param_count(kind, depths)


def test_resnet18_known_torch_count(rng):
    """Cross-check the analytic counter against the known torch total for the
    reference's ResNet-18 at num_classes=100."""
    assert analytic_param_count("basic", (2, 2, 2, 2)) == 11_220_132
    model = get_model("resnet18")
    variables = model.init(rng, jnp.zeros((1, 32, 32, 3)), train=False)
    assert n_params(variables["params"]) == 11_220_132


@pytest.mark.parametrize(
    "name,torch_count",
    [("resnet18", 11_689_512), ("resnet50", 25_557_032)],
)
def test_imagenet_stem_matches_torchvision_param_count(name, torch_count, rng):
    """stem='imagenet' (7×7/2 conv + maxpool) must reproduce the canonical
    torchvision ImageNet ResNet parameter totals exactly at
    num_classes=1000 — the strongest architecture-parity check available
    offline (the totals are torchvision's published counts)."""
    model = get_model(name, num_classes=1000, stem="imagenet")
    # small spatial input keeps CPU init cheap; param count is size-free
    variables = model.init(rng, jnp.zeros((1, 64, 64, 3)), train=False)
    assert n_params(variables["params"]) == torch_count


def test_imagenet_stem_downsamples_4x(rng):
    """7×7/2 conv + 3×3/2 maxpool: a 224 input must enter stage 1 at 56
    and leave stage 4 at 7.  The head's global mean pool erases spatial
    size, so probe real intermediates, not the logits shape."""
    model = get_model("resnet18", stem="imagenet")
    x = jnp.zeros((1, 224, 224, 3))
    variables = model.init(rng, x, train=False)
    logits, mods = model.apply(
        variables, x, train=False, capture_intermediates=True
    )
    inter = mods["intermediates"]
    assert logits.shape == (1, 100)
    assert inter["stage1_block0"]["__call__"][0].shape == (1, 56, 56, 64)
    assert inter["stage4_block1"]["__call__"][0].shape == (1, 7, 7, 512)


def test_train_mode_updates_batch_stats(rng):
    model = get_model("resnet18")
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
    variables = model.init(rng, x, train=False)
    logits, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    assert logits.shape == (4, 100)
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    changed = any(not jnp.allclose(b, a) for b, a in zip(before, after))
    assert changed, "train-mode forward must update running BN stats"


def test_eval_mode_deterministic(rng):
    model = get_model("resnet18")
    x = jax.random.normal(jax.random.key(2), (4, 32, 32, 3))
    variables = model.init(rng, x, train=False)
    a = model.apply(variables, x, train=False)
    b = model.apply(variables, x, train=False)
    assert jnp.array_equal(a, b)


def test_bf16_policy_fp32_logits(rng):
    model = get_model("resnet18", dtype=jnp.bfloat16)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(rng, x, train=False)
    # params stay fp32 (master copy), logits come back fp32
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(variables["params"]))
    logits = model.apply(variables, x, train=False)
    assert logits.dtype == jnp.float32


@pytest.mark.slow
def test_bn_stats_fp32_by_default_under_bf16(rng):
    """Under the bf16 policy, BN statistics reduce in fp32 by default
    (norm_dtype=fp32); norm_dtype=None opts back into compute-dtype stats.
    The two short-run forward passes must stay close (same math, different
    reduction precision) but the fp32 path is the accuracy-safe default."""
    x = jax.random.normal(jax.random.key(3), (16, 32, 32, 3))
    outs = {}
    for tag, norm_dtype in (("fp32", jnp.float32), ("compute", None)):
        model = get_model("resnet18", dtype=jnp.bfloat16, norm_dtype=norm_dtype)
        variables = model.init(rng, x, train=False)
        logits, _ = model.apply(variables, x, train=True, mutable=["batch_stats"])
        assert jnp.all(jnp.isfinite(logits))
        outs[tag] = logits
    # same init → bf16-stats trajectory tracks fp32-stats within bf16 noise
    np.testing.assert_allclose(outs["fp32"], outs["compute"], atol=0.15, rtol=0.1)
    assert not jnp.array_equal(outs["fp32"], outs["compute"]), (
        "bf16 stat reduction should differ at bit level — if identical, the "
        "norm_dtype knob is not reaching BatchNorm"
    )


def test_num_classes_override(rng):
    model = get_model("resnet18", num_classes=10)
    variables = model.init(rng, jnp.zeros((1, 32, 32, 3)), train=False)
    logits = model.apply(variables, jnp.zeros((3, 32, 32, 3)), train=False)
    assert logits.shape == (3, 10)


def test_unknown_model_raises():
    with pytest.raises(ValueError):
        get_model("alexnet")


@pytest.mark.slow
def test_remat_reduces_compiled_temp_memory(rng):
    """--remat must actually lower XLA's peak temp allocation for the
    backward pass (checked via compiled memory_analysis, no device run)."""
    import jax
    from distributed_training_comparison_tpu.models.resnet import BasicBlock, ResNet

    def temp_bytes(remat):
        model = ResNet(
            block=BasicBlock, num_blocks=(0, 0, 1, 1), num_classes=10, remat=remat
        )
        x = jnp.zeros((32, 32, 32, 3))
        variables = model.init(rng, x, train=False)

        def loss(params):
            logits, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            return logits.sum()

        lowered = jax.jit(jax.grad(loss)).lower(variables["params"])
        return lowered.compile().memory_analysis().temp_size_in_bytes

    plain, rematted = temp_bytes(False), temp_bytes(True)
    assert rematted < plain, (rematted, plain)
