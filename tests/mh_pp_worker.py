"""Multi-host pipeline-parallel worker: one JAX process of a 2-process CPU
'cluster' training a ViT with ``--parallel-style pipeline`` where the two
pipeline stages live on DIFFERENT processes — every per-tick ``ppermute``
activation handoff crosses the process boundary (the CPU stand-in for a
cross-host DCN hop), and the stage-sharded stacked parameters are
partitioned across processes (exercising the symmetric checkpoint fetch).

Launched by tests/test_multihost.py (4 virtual CPU devices per process →
an 8-device (4 data × 2 model) mesh, ViT depth 2 → 1 layer per stage).
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU plugin


def main(rank: int, port: int, ckpt_dir: str) -> None:
    from distributed_training_comparison_tpu.config import load_config
    from distributed_training_comparison_tpu.models import ViT
    from distributed_training_comparison_tpu.parallel import init_distributed
    from distributed_training_comparison_tpu.parallel.sharding import (
        needs_collective_fetch,
    )
    from distributed_training_comparison_tpu.train import Trainer

    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data",
            "--limit-examples", "128",
            "--batch-size", "32",
            "--epoch", "1",
            "--eval-step", "2",
            "--lr", "0.01",
            "--ckpt-path", ckpt_dir,
            "--model", "vit_tiny",  # name only; tiny stand-in passed below
            "--model-parallel", "2",
            "--parallel-style", "pipeline",
            "--pipeline-microbatches", "2",
            "--world-size", "2",
            "--rank", str(rank),
            "--dist-url", f"127.0.0.1:{port}",
        ],
    )
    init_distributed(hp)
    assert jax.process_count() == 2

    trainer = Trainer(hp, model=ViT(depth=2, dim=32, heads=2, patch=8))
    # the stacked trunk must genuinely partition across the processes
    assert needs_collective_fetch(trainer.state.params)

    version = trainer.fit()
    results = trainer.test()
    trainer.close()
    print(
        f"RESULT rank={rank} version={version} "
        f"top1={results['test_top1']:.4f} loss={results['test_loss']:.6f}",
        flush=True,
    )


if __name__ == "__main__":
    main(int(sys.argv[1]), int(sys.argv[2]), sys.argv[3])
