"""Serving-fleet tests: SLO classes, continuous batching, the router,
and the persisted AOT warm-start (tier-1 fast).

The contracts pinned here, each matching a production claim the README
makes:

- **SLO classes** (`batcher.ClassQueue`): priority-ordered dispatch, the
  class-aware shed decision (a full queue evicts the least important
  queued work for a more important newcomer), deadline expiry enforced
  at TAKE time — an expired request never burns a bucket slot and bumps
  the ``serve/shed_total`` counter.
- **Continuous admission**: a lone queued request dispatches at the next
  step boundary, not after the bucketed window.
- **Router** (`serve/router.py`): drain-on-preempt completes in-flight
  futures and re-routes queued work with zero lost requests; a replica
  declared dead fails its in-flight futures typed (``ReplicaDead``) and
  the survivors absorb the queue; ``rewarm_serve`` reaches every ready
  replica.
- **Persisted AOT warm-start** (`utils.PersistedServeCache`): a fresh
  engine — and a REAL fresh process — finds the first process's
  executables by the CompileMonitor's cross-process fingerprint and
  compiles nothing (every compile event in its stream carries
  ``cache: "persisted"``); donated executables are refused at the store
  site (the ``_compat.donated_cache_write_barred`` jax-pin bug), and a
  torn blob degrades to a recompile, never a wedge.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))

import run_report  # noqa: E402

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.obs.exporter import (
    render_openmetrics,
    split_labels,
)
from distributed_training_comparison_tpu.ops.policy import serve_actions
from distributed_training_comparison_tpu.resilience.faults import (
    CHAOS_SCENARIOS,
    check_chaos_expectations,
)
from distributed_training_comparison_tpu.serve import (
    ClassQueue,
    DeadlineExceeded,
    MicroBatcher,
    QueueOverflow,
    ReplicaDead,
    ServeEngine,
    ServeMetrics,
    ServeRouter,
    SLOClassError,
    parse_slo_classes,
    plan_serve,
)
from distributed_training_comparison_tpu.serve.router import (
    DEAD,
    READY,
    STOPPED,
)
from distributed_training_comparison_tpu.utils import (
    DonatedExecutableError,
    PersistedServeCache,
)

from test_train import TinyNet

IMG = 16


def _img():
    return np.zeros((4, 4, 3), np.uint8)


def _wait(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# ------------------------------------------------------------ SLO classes


def test_parse_slo_classes_grammar():
    table = parse_slo_classes(
        "gold:priority=0:deadline_ms=250:target=0.99,batch:priority=2"
    )
    assert table["gold"].priority == 0
    assert table["gold"].deadline_ms == 250.0
    assert table["gold"].target == 0.99
    assert table["batch"].priority == 2 and table["batch"].deadline_ms is None
    # class-less submit() keeps working: a default class is appended
    assert "default" in table
    assert set(parse_slo_classes("")) == {"default"}


@pytest.mark.parametrize(
    "spec",
    [
        "gold:badfield=1",          # unknown field
        "gold:priority=x",          # not a number
        "gold:target=1.5",          # target out of [0, 1]
        "gold:deadline_ms=0",       # deadline must be > 0
        "gold:priority=0,gold:priority=1",  # duplicate class
    ],
)
def test_parse_slo_classes_rejects(spec):
    with pytest.raises(SLOClassError):
        parse_slo_classes(spec)


def test_class_queue_priority_orders_dispatch():
    classes = parse_slo_classes("gold:priority=0,bulk:priority=2")
    q = ClassQueue(classes=classes, limit=16)
    for _ in range(3):
        q.submit(_img(), cls="bulk")
    gold = q.submit(_img(), cls="gold")
    batch = q.take(2, continuous=True)
    # the gold request queued LAST dispatches FIRST
    assert batch[0][1] is gold
    assert batch[1][1].cls == "bulk"
    q.close(drain=False)


def test_class_queue_sheds_least_important_for_newcomer():
    classes = parse_slo_classes("gold:priority=0,bulk:priority=2")
    m = ServeMetrics()
    q = ClassQueue(classes=classes, limit=2, metrics=m)
    q.submit(_img(), cls="bulk")
    victim = q.submit(_img(), cls="bulk")
    gold = q.submit(_img(), cls="gold")  # full queue: evicts newest bulk
    with pytest.raises(QueueOverflow):
        victim.result(timeout=1)
    assert not gold.done()
    # a newcomer nothing outranks is shed synchronously instead
    with pytest.raises(QueueOverflow):
        q.submit(_img(), cls="bulk")
    assert m.shed == 2  # the evicted victim + the refused newcomer
    assert q.depth == 2
    q.close(drain=False)


def test_future_resolution_is_atomic_first_wins():
    from distributed_training_comparison_tpu.serve import ServeFuture

    fut = ServeFuture(time.monotonic(), None)
    assert fut.set_error(ReplicaDead("first")) is True
    assert fut.set_result(np.zeros(4)) is False  # loser: must not record
    with pytest.raises(ReplicaDead):
        fut.result(timeout=1)
    fut2 = ServeFuture(time.monotonic(), None)
    assert fut2.set_result(np.ones(2)) is True
    assert fut2.set_error(ReplicaDead("late")) is False
    assert (fut2.result(timeout=1) == 1).all()


def test_unknown_class_is_typed():
    q = ClassQueue(limit=4)
    with pytest.raises(SLOClassError):
        q.submit(_img(), cls="nonexistent")
    q.close(drain=False)


# ------------------------------- satellite: expiry at take, never after


class _StubEngine:
    """Engine stand-in with a controllable service time."""

    max_bucket = 8
    buckets = (8,)

    def __init__(self, delay_s=0.0, rid=0):
        self.delay_s = delay_s
        self.rid = rid
        self.calls = []
        self.rewarms = 0

    def warmup(self, buckets=None):
        return None

    def rewarm(self, buckets=None):
        self.rewarms += 1
        return {"warmed": list(buckets or self.buckets)}

    def stats(self):
        return {
            "buckets": list(self.buckets), "compiles": 0, "cache_hits": 0,
            "persisted_hits": 0, "bucket_counts": {8: 0},
        }

    def predict_logits(self, imgs):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.calls.append(len(imgs))
        return np.zeros((len(imgs), 4), np.float32)


def test_expired_request_never_burns_a_slot_and_counts_as_shed():
    reg = obs.MetricRegistry()
    m = ServeMetrics(registry=reg)
    q = ClassQueue(limit=32, metrics=m)
    doomed = q.submit(_img(), deadline_ms=1.0)
    live = [q.submit(_img()) for _ in range(8)]
    time.sleep(0.05)  # the deadline lapses while queued
    batch = q.take(8, continuous=True)
    # the expired request was failed at take time and did NOT displace
    # any of the 8 live requests from the full bucket
    assert len(batch) == 8
    assert all(f in [fut for _, fut in batch] for f in live)
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=1)
    assert m.expired == 1
    st = m.class_payload()["default"]
    assert st["expired_pre_dispatch"] == 1
    # the satellite's counter: wasted admission is shed, whatever the type
    assert reg.counter("serve/shed_total").snapshot(reset=False)["n"] == 1
    q.close(drain=False)


def test_shed_total_also_counts_queue_overflow():
    reg = obs.MetricRegistry()
    m = ServeMetrics(registry=reg)
    q = ClassQueue(limit=1, metrics=m)
    q.submit(_img())
    with pytest.raises(QueueOverflow):
        q.submit(_img())
    assert reg.counter("serve/shed_total").snapshot(reset=False)["n"] == 1
    q.close(drain=False)


def test_bucketed_window_rechecks_deadlines_before_dispatch():
    """A deadline that lapses DURING the coalescing window must fail
    pre-dispatch — the windowed path admitted it live, then out-waited
    it; it must not reach the engine as a doomed 'completed' request."""
    reg = obs.MetricRegistry()
    m = ServeMetrics(registry=reg)
    q = ClassQueue(limit=8, metrics=m)
    doomed = q.submit(_img(), deadline_ms=50.0)  # alive now, dead in 50ms
    batch = q.take(8, window_s=0.2, continuous=False)  # window > deadline
    assert batch == []  # nothing for the engine
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=1)
    st = m.class_payload()["default"]
    assert st["expired"] == 1 and st["expired_pre_dispatch"] == 1
    q.close(drain=False)


def test_continuous_admission_skips_the_window():
    # bucketed: a lone request waits out the coalescing window
    q = ClassQueue(limit=8)
    q.submit(_img())
    t0 = time.monotonic()
    batch = q.take(8, window_s=0.25, continuous=False)
    assert len(batch) == 1 and time.monotonic() - t0 >= 0.2
    q.close(drain=False)
    # continuous: the same lone request dispatches at the step boundary
    q2 = ClassQueue(limit=8)
    q2.submit(_img())
    t0 = time.monotonic()
    batch = q2.take(8, window_s=0.25, continuous=True)
    assert len(batch) == 1 and time.monotonic() - t0 < 0.2
    q2.close(drain=False)


def test_micro_batcher_continuous_mode_end_to_end():
    eng = _StubEngine(delay_s=0.02)
    with MicroBatcher(
        eng, max_wait_ms=10_000, queue_limit=64, mode="continuous"
    ) as b:
        futs = [b.submit(_img()) for _ in range(12)]
        rows = [f.result(timeout=5) for f in futs]
    # a 10-second window never gated anything: the first dispatch went
    # out immediately and later dispatches slot-filled what had queued
    assert len(rows) == 12
    assert sum(eng.calls) == 12
    with pytest.raises(ValueError):
        MicroBatcher(eng, mode="nonsense")


# ------------------------------------------------------------- the router


def _bus(tmp_path):
    bus = obs.EventBus(run_id="f" * 16)
    bus.bind_dir(tmp_path)
    return bus


def test_router_drain_on_preempt_loses_nothing(tmp_path):
    """The preemption drain: in-flight futures complete, queued work
    re-routes to the surviving replica, zero lost requests."""
    stubs = {}

    def factory(rid):
        stubs[rid] = _StubEngine(delay_s=0.08, rid=rid)
        return stubs[rid]

    bus = _bus(tmp_path)
    r = ServeRouter(factory, replicas=2, bus=bus, queue_limit=256,
                    emit_every_s=0.2)
    try:
        r.warmup()
        futs = [r.submit(_img()) for _ in range(80)]
        _wait(lambda: r.replicas[0].dispatches >= 1, what="first dispatch")
        r.drain(0)
        rows = [f.result(timeout=30) for f in futs]  # raises on any loss
        assert len(rows) == 80
        _wait(lambda: r.replicas[0].state == STOPPED,
              what="drained replica to stop")
        assert r.replicas[1].state == READY
        assert r.replicas[1].routed > 0  # the queue re-routed
        assert r.replicas[0].routed + r.replicas[1].routed == 80
    finally:
        r.close()
    states = [
        (e["payload"]["replica"], e["payload"]["state"])
        for e in obs.load_events(Path(tmp_path) / "events.jsonl")
        if e["kind"] == "replica" and "state" in e.get("payload", {})
    ]
    assert (0, "draining") in states and (0, "stopped") in states


def test_router_dead_replica_fails_inflight_typed_and_queue_survives():
    def factory(rid):
        if rid == 1:
            # replica 1 is slow to warm: replica 0 owns the early traffic
            class _Slow(_StubEngine):
                def warmup(self, buckets=None):
                    time.sleep(0.6)
            return _Slow(delay_s=0.05, rid=rid)
        return _StubEngine(delay_s=0.5, rid=rid)

    r = ServeRouter(factory, replicas=2, queue_limit=64)
    try:
        r.wait_ready(n=1, timeout=10)
        futs = [r.submit(_img()) for _ in range(9)]
        _wait(lambda: r.replicas[0]._inflight, what="in-flight batch")
        failed = r.replicas[0].mark_dead("test verdict")
        assert failed >= 1
        assert r.replicas[0].state == DEAD
        outcomes = {"dead": 0, "ok": 0}
        for f in futs:
            try:
                f.result(timeout=30)
                outcomes["ok"] += 1
            except ReplicaDead:
                outcomes["dead"] += 1
        # exactly the in-flight futures failed typed; everything queued
        # (never pinned to the dead replica) completed on the survivor
        assert outcomes["dead"] == failed
        assert outcomes["ok"] == 9 - failed
    finally:
        r.close()


def test_router_gives_up_when_the_whole_fleet_is_gone(tmp_path):
    """Every replica dead while the queue is open: queued futures fail
    typed instead of hanging, the door closes, give_up hits the stream."""
    bus = _bus(tmp_path)
    r = ServeRouter(
        lambda rid: _StubEngine(delay_s=0.5), replicas=1, bus=bus,
        queue_limit=64,
    )
    try:
        r.warmup()
        futs = [r.submit(_img()) for _ in range(20)]  # 8 in flight, 12 queued
        _wait(lambda: r.replicas[0]._inflight, what="in-flight batch")
        r.replicas[0].mark_dead("test verdict")
        r.health_check()  # the ticker's give-up pass, run directly
        for f in futs:
            with pytest.raises(ReplicaDead):
                f.result(timeout=10)  # nothing may hang
        from distributed_training_comparison_tpu.serve import BatcherClosed

        with pytest.raises(BatcherClosed):
            r.submit(_img())
    finally:
        r.close()
    give_ups = [
        e["payload"] for e in obs.load_events(Path(tmp_path) / "events.jsonl")
        if e["kind"] == "serve_route" and e["payload"].get("state") == "give_up"
    ]
    assert len(give_ups) == 1
    assert give_ups[0]["queued_failed"] > 0
    # every abandoned request is a terminal per-class failure
    assert r.metrics.failed == 20


def test_dead_replica_dispatch_does_not_double_count():
    """mark_dead fails the in-flight futures; when the still-running
    dispatch later produces their results, it must NOT also record them
    completed (the attainment gate would count each request twice)."""
    r = ServeRouter(
        lambda rid: _StubEngine(delay_s=0.4), replicas=1, queue_limit=16,
    )
    try:
        r.warmup()
        futs = [r.submit(_img()) for _ in range(4)]
        _wait(lambda: r.replicas[0]._inflight, what="in-flight batch")
        failed = r.replicas[0].mark_dead("test verdict")
        assert failed >= 1
        for f in futs:
            with pytest.raises(ReplicaDead):
                f.result(timeout=10)
        time.sleep(0.6)  # let the doomed dispatch finish
        assert r.metrics.completed == 0
        assert all(
            row["completed"] == 0 and row["ok_deadline"] == 0
            for row in r.metrics.class_payload().values()
        )
        # the failures LAND in the SLO denominator: attainment reads
        # 0.0, not "all targets met over vanished traffic"
        row = r.metrics.class_payload()["default"]
        assert row["failed"] == 4
        assert row["attainment"] == 0.0
    finally:
        r.close()


def test_router_rewarm_reaches_every_ready_replica():
    stubs = {}

    def factory(rid):
        stubs[rid] = _StubEngine(rid=rid)
        return stubs[rid]

    r = ServeRouter(factory, replicas=2, queue_limit=16)
    try:
        r.warmup()
        report = serve_actions(r)["rewarm_serve"]({})
        assert set(report["replicas"]) == {"0", "1"}
        assert all(s.rewarms == 1 for s in stubs.values())
    finally:
        r.close()


def test_router_arms_sentinel_after_fleet_warmup_not_per_engine():
    """N replicas warm one shared monitor in parallel: the first
    finisher must not arm the sentinel while its siblings are still
    paying genuine warmup compiles — the router arms once, after the
    whole fleet warmed."""
    monitor = obs.CompileMonitor(
        bus=obs.EventBus(run_id="e" * 16), registry=obs.MetricRegistry()
    )
    eng = ServeEngine(
        model=TinyNet(num_classes=10), buckets=(2,), precision="fp32",
        image_size=IMG, monitor=monitor, arm_sentinel=False,
    )
    eng.warmup()
    assert not monitor.is_warm  # deferred: the engine did NOT arm it
    r = ServeRouter(
        lambda rid: _StubEngine(rid=rid), replicas=2, monitor=monitor,
        queue_limit=8,
    )
    try:
        r.warmup()
        assert monitor.is_warm  # the router armed it at the barrier
    finally:
        r.close()


def test_serve_class_table_sums_across_routers(tmp_path):
    """Two sequential routers in one process (distinct `router` tokens):
    their cumulative counters SUM instead of the last one winning."""
    bus = obs.EventBus(run_id="f" * 16)
    bus.bind_dir(tmp_path)
    row = {"completed": 3, "ok_deadline": 3, "expired": 0, "shed": 0,
           "failed": 0, "priority": 0, "deadline_ms": 50.0, "target": 0.5}
    bus.emit("serve_route", state="routing", router=0,
             classes={"gold": dict(row)})
    bus.emit("serve_route", state="final", router=1,
             classes={"gold": dict(row, completed=7, ok_deadline=6,
                                   failed=1)})
    table = run_report.serve_class_table(
        obs.load_events(Path(tmp_path) / "events.jsonl")
    )
    assert table["gold"]["completed"] == 10  # 3 + 7, not last-wins 7
    assert table["gold"]["ok_deadline"] == 9
    assert table["gold"]["failed"] == 1
    # failures sit in the denominator: 9 ok of 11 terminal
    assert abs(table["gold"]["attainment"] - 9 / 11) < 1e-9


def test_router_validates_flags():
    with pytest.raises(ValueError):
        ServeRouter(lambda rid: _StubEngine(), replicas=0)
    with pytest.raises(ValueError):
        ServeRouter(lambda rid: _StubEngine(), replicas=1, mode="nope")


# -------------------------------------------- persisted AOT warm-start


@pytest.fixture
def private_jax_cache(tmp_path):
    """A fresh, empty jax HLO cache for the duration of one test: the
    warm-start contract needs the first engine's build to be a REAL
    compile (an executable materialized from a warm HLO cache serializes
    into a blob whose fusion symbols are missing on this jaxlib — the
    store-time round-trip verify refuses it, by design)."""
    import jax

    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "jax"))
    yield
    jax.config.update("jax_compilation_cache_dir", old)


def test_persisted_cache_warm_starts_fresh_engine_by_fingerprint(
    tmp_path, private_jax_cache
):
    aot = PersistedServeCache(tmp_path / "aot")
    bus1 = obs.EventBus(run_id="a" * 16)
    bus1.bind_dir(tmp_path / "p1")
    reg1 = obs.MetricRegistry()
    e1 = ServeEngine(
        model=TinyNet(num_classes=10), buckets=(2, 4), precision="fp32",
        image_size=IMG, monitor=obs.CompileMonitor(bus=bus1, registry=reg1),
        aot_cache=aot,
    )
    e1.warmup()
    assert e1.stats()["compiles"] == 2
    assert aot.stats()["stores"] == 2 and aot.stats()["rejected"] == 0

    # a FRESH engine + monitor against the same store: zero compiles,
    # every ladder entry deserialized by fingerprint, and the stream
    # carries only `cache: "persisted"` compile events
    bus2 = obs.EventBus(run_id="b" * 16)
    bus2.bind_dir(tmp_path / "p2")
    reg2 = obs.MetricRegistry()
    e2 = ServeEngine(
        model=TinyNet(num_classes=10), buckets=(2, 4), precision="fp32",
        image_size=IMG, monitor=obs.CompileMonitor(bus=bus2, registry=reg2),
        aot_cache=PersistedServeCache(tmp_path / "aot"),
    )
    e2.warmup()
    assert e2.stats()["compiles"] == 0
    assert e2.stats()["persisted_hits"] == 2
    evs = obs.load_events(tmp_path / "p2" / "events.jsonl")
    comp = [e["payload"] for e in evs if e["kind"] == "compile"]
    assert len(comp) == 2
    assert all(p["cache"] == "persisted" for p in comp)
    # a millisecond deserialization must never page the recompile
    # sentinel (rewarm_serve exists for real compile cliffs)
    assert not any(p.get("recompile_after_warmup") for p in comp)
    # the cross-process join: the SAME fingerprints, either side
    fps1 = {
        e["payload"]["fingerprint"]
        for e in obs.load_events(tmp_path / "p1" / "events.jsonl")
        if e["kind"] == "compile"
    }
    assert {p["fingerprint"] for p in comp} == fps1
    # and the warm-started engine still computes
    out = e2.predict_logits(np.zeros((3, IMG, IMG, 3), np.uint8))
    assert out.shape == (3, 10)


def test_cold_start_real_fresh_process_hits_cache_by_fingerprint(tmp_path):
    """The bench leg's contract at test size: two REAL fresh processes
    against one persisted store — the first pays real compiles and
    stores, the restarted one compiles NOTHING (stream-judged)."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jax"),
    )
    worker = Path(__file__).parent / "serve_cold_worker.py"
    reports = {}
    for tag in ("cold", "warm"):
        proc = subprocess.run(
            [sys.executable, str(worker),
             str(tmp_path / tag), str(tmp_path / "aot")],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr[-1500:]
        reports[tag] = json.loads(proc.stdout.strip().splitlines()[-1])
    assert reports["cold"]["compiles"] == 2
    assert reports["cold"]["aot_cache"]["stores"] == 2
    assert reports["warm"]["compiles"] == 0
    assert reports["warm"]["persisted_hits"] == 2
    caches = {}
    for tag in ("cold", "warm"):
        caches[tag] = [
            (e["payload"]["fingerprint"], e["payload"]["cache"])
            for e in obs.load_events(tmp_path / tag / "events.jsonl")
            if e["kind"] == "compile"
        ]
    # judge the stream, not the self-report: the restarted process's
    # compile events are ALL persisted loads, under the same fingerprints
    assert all(c == "persisted" for _, c in caches["warm"])
    assert {f for f, _ in caches["warm"]} == {f for f, _ in caches["cold"]}
    assert all(c != "persisted" for _, c in caches["cold"])


def test_store_refuses_donated_executables(tmp_path):
    cache = PersistedServeCache(tmp_path)
    with pytest.raises(DonatedExecutableError) as ei:
        cache.store("deadbeef00000000", object(), donated=(1,))
    # the refusal names the jax-pin bug it guards against
    assert "donated_cache_write_barred" in str(ei.value)
    assert not list(Path(tmp_path).glob("*.aotexe"))


def test_torn_blob_degrades_to_recompile_and_unlinks(tmp_path):
    cache = PersistedServeCache(tmp_path)
    path = cache.path_for("feedface00000000")
    path.write_bytes(b"not a pickled executable")
    exe, load_s = cache.load("feedface00000000")
    assert exe is None
    assert cache.errors == 1
    assert not path.exists()  # poisoned entries must not wedge cold starts


# ----------------------------------------------------- ledger-fit sizing


def _serve_compile_ev(bucket, flops):
    return {
        "kind": "compile",
        "payload": {
            "name": f"serve_predict@b{bucket}", "flops": flops,
            "devices": 1, "fingerprint": "ab" * 8,
        },
    }


def test_plan_serve_sizes_replicas_and_trims_ladder():
    events = [_serve_compile_ev(8, 8e9), _serve_compile_ev(1, 1e9)]
    # no p99 target anywhere → the legacy utilization ceiling, labeled
    # with the autoscaler's own fallback name
    plan = plan_serve(events, buckets=(1, 8), rate_rps=500.0)
    assert plan["replicas"] >= 1 and plan["sized_by"] == "utilization"
    assert set(plan["per_bucket"]) == {"1", "8"}
    assert plan["per_replica_capacity_rps"] > 0
    # a deadline no bucket's service time fits keeps the smallest bucket
    # (refusing all traffic would be worse; the attainment gate surfaces it)
    tight = plan_serve(
        events, buckets=(1, 8), rate_rps=500.0,
        classes=parse_slo_classes("gold:priority=0:deadline_ms=0.000001"),
    )
    assert tight["buckets"] == [1]
    # capacity is priced from the ladder the replicas actually serve,
    # never from a deadline-trimmed-out bucket's throughput
    assert tight["best_bucket"] in tight["buckets"]
    assert tight["replicas"] >= plan["replicas"]
    # a class deadline is a p99 budget: initial sizing prices the same
    # Sakasegawa G/G/m tail the live autoscaler fits
    assert tight["sized_by"] == "ggm"
    assert tight["tail"]["targets_ms"]
    # an explicit scale target drives the same path without classes (an
    # unsaturated rate, so the G/G/m prediction is finite)
    targeted = plan_serve(
        events, buckets=(1, 8), rate_rps=50.0,
        scale_targets={"*": 10.0},  # generous 10 s p99 → small fleet
    )
    assert targeted["sized_by"] == "ggm"
    assert 1 <= targeted["replicas"] <= 8
    assert targeted["tail"]["predicted_p99_ms"] is not None
    assert targeted["tail"]["predicted_p99_ms"] <= 10_000.0
    # no serve ledger at all: one replica, honestly labeled
    empty = plan_serve([], buckets=(1, 8), rate_rps=500.0)
    assert empty["replicas"] == 1
    assert empty["sized_by"] == "no-serve-ledger"


# ------------------------------------------- run_report --serve SLO gate


def _route_event(bus, classes):
    bus.emit("serve_route", state="routing", classes=classes)


def test_serve_report_gates_on_attainment(tmp_path, capsys):
    ok_dir, bad_dir = tmp_path / "ok", tmp_path / "bad"
    for d, ok_deadline in ((ok_dir, 10), (bad_dir, 5)):
        bus = obs.EventBus(run_id="c" * 16)
        bus.bind_dir(d)
        _route_event(bus, {
            "gold": {
                "completed": 10, "ok_deadline": ok_deadline, "expired": 0,
                "shed": 0, "priority": 0, "deadline_ms": 100.0,
                "target": 0.9,
            },
            "bulk": {
                "completed": 5, "ok_deadline": 5, "expired": 0, "shed": 0,
                "priority": 2, "deadline_ms": None, "target": 0.0,
            },
        })
    assert run_report.serve_report(ok_dir) == 0
    assert "all SLO targets met" in capsys.readouterr().out
    assert run_report.serve_report(bad_dir) == 1
    assert "BELOW TARGET" in capsys.readouterr().out
    # an empty root is an error; a root with no serving session is not
    assert run_report.serve_report(tmp_path / "void") == 2


def test_serve_class_table_sums_sessions_cumulative_last_wins(tmp_path):
    bus = obs.EventBus(run_id="d" * 16)
    bus.bind_dir(tmp_path)
    row = {"completed": 3, "ok_deadline": 3, "expired": 0, "shed": 0,
           "priority": 0, "deadline_ms": 50.0, "target": 0.5}
    _route_event(bus, {"gold": dict(row)})
    _route_event(bus, {"gold": dict(row, completed=7, ok_deadline=6)})
    table = run_report.serve_class_table(
        obs.load_events(Path(tmp_path) / "events.jsonl")
    )
    # cumulative semantics: the LAST event of the session wins, not the sum
    assert table["gold"]["completed"] == 7
    assert table["gold"]["ok_deadline"] == 6


# ------------------------------------------ per-class OpenMetrics labels


def test_split_labels_grammar():
    assert split_labels("serve/latency_s{class=gold}") == (
        "serve/latency_s", {"class": "gold"}
    )
    assert split_labels("serve/latency_s") == ("serve/latency_s", {})
    # non-label brace junk passes through untouched
    assert split_labels("weird{notlabels}") == ("weird{notlabels}", {})


def test_render_openmetrics_groups_label_variants_into_one_family():
    m = ServeMetrics()
    m.record_request_done(0.010, cls="gold")
    m.record_request_done(0.020, cls="bulk")
    m.record_request_done(0.015)
    snaps = {}
    for st in m._class_stats.values():
        snaps[st.hist.name] = st.hist.snapshot(reset=False)
    snaps["serve/latency_s"] = m._latency_hist.snapshot(reset=False)
    snaps["serve/shed_total{class=gold}"] = {"type": "counter", "n": 2}
    text = render_openmetrics(metrics=snaps)
    # ONE # TYPE line for the shared family, every variant under it
    assert text.count("# TYPE dtc_serve_latency_s histogram") == 1
    assert 'dtc_serve_latency_s_count{class="gold"} 1' in text
    assert 'dtc_serve_latency_s_count{class="bulk"} 1' in text
    assert "dtc_serve_latency_s_count 3" in text  # the unlabeled global
    assert 'dtc_serve_shed_total_total{class="gold"} 2' in text
    assert text.rstrip().endswith("# EOF")


# ----------------------------------------------- chaos + flag validation


def test_serve_flash_rewarm_scenario_is_registered():
    sc = CHAOS_SCENARIOS["serve_flash_rewarm"]
    assert sc["session"] == "serve"
    assert "serve_route" in sc["require_kinds"]
    assert "--serve" in sc["extra_args"]
    # the expectation block is satisfiable by a green run...
    observed = {
        "final_rc": 0, "alerts_fired": 2, "policy_completed": 1,
        "recompiles": 1, "p99_recovered": True, "policy_dry_run": 0,
    }
    assert check_chaos_expectations(sc["expect"], observed) == []
    # ...and actually binds on the recovery claim
    assert check_chaos_expectations(
        sc["expect"], dict(observed, p99_recovered=False)
    )


def test_serve_fleet_flags_parse_and_validate():
    hp = load_config("tpu", argv=[
        "--serve", "--serve-replicas", "2", "--serve-mode", "bucketed",
        "--serve-buckets", "1,4,8", "--serve-warm-buckets", "4,1",
        "--serve-classes", "gold:priority=0:deadline_ms=250:target=0.99",
        "--serve-shape", "flash", "--serve-flash-mult", "4",
    ])
    assert hp.serve_replicas == 2 and hp.serve_mode == "bucketed"
    assert hp.serve_warm_buckets == (1, 4)
    with pytest.raises(SystemExit):  # warm bucket outside the ladder
        load_config("tpu", argv=[
            "--serve-buckets", "1,4", "--serve-warm-buckets", "8",
        ])
    with pytest.raises(SystemExit):  # malformed class spec dies at the CLI
        load_config("tpu", argv=["--serve-classes", "gold:bogus=1"])
    with pytest.raises(SystemExit):  # negative replica count
        load_config("tpu", argv=["--serve-replicas", "-1"])
