"""Switch-MoE FFN (models/moe.py) + expert parallelism.

Beyond parity (the reference is CNN-only): routing/dispatch math against
hand-computable cases, the sown load-balance loss, capacity-overflow
dropping, and the EP sharding + training path on the virtual mesh.
"""

import flax.linen as lnn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_comparison_tpu import models, parallel
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.models import SwitchFFN
from distributed_training_comparison_tpu.train import (
    Trainer,
    configure_optimizers,
    create_train_state,
    make_train_step,
)


class HP:
    lr = 0.1
    weight_decay = 1e-4
    lr_decay_step_size = 25
    lr_decay_gamma = 0.1


def _ffn(num_experts=2, dim=16, capacity_factor=1.0):
    return SwitchFFN(
        dim=dim, num_experts=num_experts, mlp_ratio=2,
        capacity_factor=capacity_factor,
    )


def test_single_expert_equals_dense_mlp():
    """With one expert the router is a constant softmax (gate == 1) and
    capacity covers every token: the layer must equal the expert-0 MLP
    applied densely — pinning the dispatch/combine one-hot algebra."""
    ffn = _ffn(num_experts=1, capacity_factor=1.0)
    x = jax.random.normal(jax.random.key(0), (2, 12, 16))
    vars_ = ffn.init(jax.random.key(1), x)
    out = ffn.apply(vars_, x)

    p = vars_["params"]
    h = jnp.einsum("bsd,dh->bsh", x, p["w_up"][0]) + p["b_up"][0]
    dense = jnp.einsum("bsh,hd->bsd", lnn.gelu(h), p["w_down"][0]) + p["b_down"][0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=1e-5)


def test_capacity_overflow_drops_tokens():
    """Zeroed router → uniform probs, argmax ties to expert 0, so all n
    tokens route there while capacity is only ~n/2: tokens past capacity
    must contribute exactly zero (Switch drop semantics), earlier tokens
    pass gate-weighted expert output."""
    ffn = _ffn(num_experts=2, capacity_factor=1.0)
    x = jax.random.normal(jax.random.key(0), (1, 32, 16))
    vars_ = ffn.init(jax.random.key(1), x)
    p = jax.tree_util.tree_map(jnp.asarray, vars_["params"])
    p["router"]["kernel"] = jnp.zeros_like(p["router"]["kernel"])
    p["router"]["bias"] = jnp.zeros_like(p["router"]["bias"])
    out = ffn.apply({"params": p}, x)[0]  # (32, 16)

    cap = 16  # ceil(32 * 1.0 / 2) = 16 (already a multiple of 8)
    dropped = np.linalg.norm(np.asarray(out[cap:]), axis=-1)
    kept = np.linalg.norm(np.asarray(out[:cap]), axis=-1)
    np.testing.assert_allclose(dropped, 0.0, atol=1e-7)
    assert (kept > 1e-3).all()
    # kept tokens carry the tied gate probability 0.5
    h = jnp.einsum("sd,dh->sh", x[0, :cap], p["w_up"][0]) + p["b_up"][0]
    expert0 = jnp.einsum("sh,hd->sd", lnn.gelu(h), p["w_down"][0]) + p["b_down"][0]
    np.testing.assert_allclose(
        np.asarray(out[:cap]), 0.5 * np.asarray(expert0), atol=1e-5
    )


def test_gather_and_onehot_dispatch_agree():
    """The sort/gather dispatch must reproduce the one-hot matmul
    formulation exactly (same routing, same drops, same gating) — a stable
    sort preserves within-expert original token order, so the kept sets
    match the cumsum formulation."""
    import dataclasses

    base = _ffn(num_experts=4, dim=16, capacity_factor=0.5)  # force drops
    x = jax.random.normal(jax.random.key(5), (2, 64, 16))
    vars_ = base.init(jax.random.key(6), x)
    out_g = dataclasses.replace(base, dispatch="gather").apply(vars_, x)
    out_o = dataclasses.replace(base, dispatch="onehot").apply(vars_, x)
    np.testing.assert_allclose(
        np.asarray(out_g), np.asarray(out_o), atol=2e-6
    )
    # under the bf16 policy (the bench configuration) the two paths apply
    # the gate in different dtypes; they must still agree at bf16 tolerance
    bf16 = dataclasses.replace(base, dtype=jnp.bfloat16)
    g16 = dataclasses.replace(bf16, dispatch="gather").apply(vars_, x)
    o16 = dataclasses.replace(bf16, dispatch="onehot").apply(vars_, x)
    np.testing.assert_allclose(
        np.asarray(g16, dtype=np.float32), np.asarray(o16, dtype=np.float32),
        atol=3e-2,
    )
    with pytest.raises(ValueError, match="unknown MoE dispatch"):
        dataclasses.replace(base, dispatch="nope").apply(vars_, x)


def test_gmm_dispatch_matches_gather():
    """The Pallas grouped-matmul dispatch (interpret mode on CPU) must
    reproduce the sort/gather formulation: same routing, same capacity
    drops, same gating — outputs to fp32 roundoff, and the same gradients
    for every parameter (the custom VJP mirrors XLA's einsum autodiff)."""
    import dataclasses

    base = _ffn(num_experts=4, dim=16, capacity_factor=0.5)  # force drops
    x = jax.random.normal(jax.random.key(5), (2, 64, 16))
    vars_ = base.init(jax.random.key(6), x)
    out_g = dataclasses.replace(base, dispatch="gather").apply(vars_, x)
    out_k = dataclasses.replace(base, dispatch="gmm").apply(vars_, x)
    np.testing.assert_allclose(
        np.asarray(out_g), np.asarray(out_k), atol=2e-6
    )

    def loss(v, dispatch):
        m = dataclasses.replace(base, dispatch=dispatch)
        return jnp.sum(m.apply(v, x) ** 2)

    g_g = jax.grad(loss)(vars_, "gather")
    g_k = jax.grad(loss)(vars_, "gmm")
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(g_g),
        jax.tree_util.tree_leaves_with_path(g_k),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5,
            err_msg=jax.tree_util.keystr(path),
        )


def test_gmm_empty_expert_groups():
    """A router biased so hard that several experts (including the last)
    receive zero tokens: the kernel's per-expert overlap guards and the
    dW index-map clamp must handle empty groups at both ends — the
    regression shape for the out-of-range tile DMA when starts[e] == n."""
    import dataclasses

    base = _ffn(num_experts=4, dim=16, capacity_factor=4.0)
    x = jax.random.normal(jax.random.key(7), (1, 48, 16))
    vars_ = base.init(jax.random.key(8), x)
    p = jax.tree_util.tree_map(jnp.asarray, vars_["params"])
    # all logits mass on expert 1: experts 0, 2, 3 get zero tokens
    p["router"]["kernel"] = jnp.zeros_like(p["router"]["kernel"])
    p["router"]["bias"] = jnp.asarray([-100.0, 100.0, -100.0, -100.0])
    vs = {"params": p}

    def loss(v, dispatch):
        m = dataclasses.replace(base, dispatch=dispatch)
        return jnp.sum(m.apply(v, x) ** 2)

    out_g = dataclasses.replace(base, dispatch="gather").apply(vs, x)
    out_k = dataclasses.replace(base, dispatch="gmm").apply(vs, x)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_k), atol=2e-6)
    g_g = jax.grad(loss)(vs, "gather")["params"]
    g_k = jax.grad(loss)(vs, "gmm")["params"]
    np.testing.assert_allclose(
        np.asarray(g_g["w_up"]), np.asarray(g_k["w_up"]), atol=5e-5
    )
    # untouched experts get exactly zero weight gradient from both paths
    assert float(jnp.abs(g_k["w_up"][0]).max()) == 0.0
    assert float(jnp.abs(g_k["w_up"][3]).max()) == 0.0


def test_auto_dispatch_resolves_by_backend():
    """dispatch="auto" (the default) must resolve to the XLA sort/gather
    path off-TPU — bit-identical outputs on the CPU CI backend."""
    import dataclasses

    base = _ffn(num_experts=4, dim=16)
    x = jax.random.normal(jax.random.key(9), (2, 32, 16))
    vars_ = base.init(jax.random.key(10), x)
    assert base.dispatch == "auto"
    out_a = base.apply(vars_, x)
    out_g = dataclasses.replace(base, dispatch="gather").apply(vars_, x)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_g), atol=0)


def test_aux_loss_sown_and_balanced_value():
    """The Switch load-balance loss E·Σ_e f_e·P_e lands in the "losses"
    collection when mutable, is ≥ aux_weight (equality at perfect
    balance), and sow is a no-op when the collection is not mutable."""
    ffn = _ffn(num_experts=4, dim=16)
    x = jax.random.normal(jax.random.key(2), (2, 64, 16))
    vars_ = ffn.init(jax.random.key(3), x)
    out, mutated = ffn.apply(vars_, x, mutable=["losses"])
    (aux,) = jax.tree_util.tree_leaves(mutated["losses"])
    # E·Σ f·p == 1 at perfect balance; routing noise pushes it above
    assert 0.9 * 0.01 <= float(aux) < 4 * 0.01
    # not mutable → no-op, same output
    out2 = ffn.apply(vars_, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=0)


def test_vit_moe_trains_under_expert_parallelism():
    """vit_moe end to end on a 4×2 mesh: the expert axis shards over
    "model" (EP), the aux loss joins the objective, and two steps reduce
    the loss."""
    model = models.get_model("vit_moe", depth=2)
    mesh = parallel.make_mesh(4, 2, backend="tpu")
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(model, jax.random.key(0), tx)
    sharding = parallel.state_shardings(mesh, state)
    from jax.sharding import PartitionSpec as P

    assert sharding.params["blocks"]["moe"]["w_up"].spec == P(
        None, "model", None, None
    )
    assert sharding.params["blocks"]["moe"]["router"]["kernel"].spec == P()
    state = parallel.place_tree(state, sharding)
    step = make_train_step(mesh, state_sharding=sharding)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, (32, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 100, (32,), dtype=np.int32)
    bx, by = parallel.shard_batch((x, y), mesh)
    losses = []
    for i in range(3):
        state, metrics = step(state, bx, by, jax.random.key(5))
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_aux_loss_joins_objective():
    """The loss a train step reports must equal cross-entropy PLUS the sown
    per-block aux losses — computed independently through a manual apply
    with the "losses" collection mutable."""
    from distributed_training_comparison_tpu.data.augment import normalize_images
    from distributed_training_comparison_tpu.train.step import _cross_entropy

    mesh = parallel.make_mesh(4, 2, backend="tpu")
    model = models.get_model("vit_moe", depth=2)
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(model, jax.random.key(0), tx)
    sharding = parallel.state_shardings(mesh, state)
    state = parallel.place_tree(state, sharding)
    rng = np.random.default_rng(1)
    x = rng.integers(0, 255, (16, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 100, (16,), dtype=np.int32)
    bx, by = parallel.shard_batch((x, y), mesh)

    step = make_train_step(mesh, augment=False, state_sharding=sharding)
    _, metrics = step(state, bx, by, jax.random.key(2))
    reported = float(metrics["loss"])

    xn = normalize_images(jnp.asarray(x))
    logits, mutated = state.apply_fn(
        {"params": state.params, "batch_stats": state.batch_stats},
        xn, train=True, mutable=["batch_stats", "losses"],
    )
    ce = float(_cross_entropy(logits, jnp.asarray(y)).mean())
    aux = float(
        sum(jnp.sum(l) for l in jax.tree_util.tree_leaves(mutated["losses"]))
    )
    assert aux > 0
    assert reported == pytest.approx(ce + aux, rel=1e-5)


def test_capacity_pads_to_compute_dtype_tile():
    """Capacity padding follows the compute dtype's sublane tile (8 rows
    fp32, 16 bf16 — ADVICE r4): with a zeroed router (all n=80 tokens tie
    to expert 0 of 2) and cf=0.5, raw capacity is 20 → 24 kept under
    fp32, 32 kept under bf16.  Observable through the drop boundary."""
    import dataclasses

    x = jax.random.normal(jax.random.key(0), (1, 80, 16))
    for dtype, want_kept in ((jnp.float32, 24), (jnp.bfloat16, 32)):
        ffn = dataclasses.replace(_ffn(num_experts=2, capacity_factor=0.5), dtype=dtype)
        vars_ = ffn.init(jax.random.key(1), x)
        p = jax.tree_util.tree_map(jnp.asarray, vars_["params"])
        p["router"]["kernel"] = jnp.zeros_like(p["router"]["kernel"])
        p["router"]["bias"] = jnp.zeros_like(p["router"]["bias"])
        out = ffn.apply({"params": p}, x)[0]
        kept = int(jnp.sum(jnp.linalg.norm(out.astype(jnp.float32), axis=-1) > 1e-3))
        assert kept == want_kept, (dtype, kept)


def test_routing_health_sown_values():
    """Forced router collapse (zeroed router → argmax ties to expert 0):
    the sown "moe_metrics" must read dropped_frac = (n-cap)/n and
    expert_load = one-hot on expert 0 — the observability contract
    (VERDICT r4: a collapsed router was invisible in the logs)."""
    ffn = _ffn(num_experts=2, capacity_factor=1.0)
    x = jax.random.normal(jax.random.key(0), (1, 32, 16))
    vars_ = ffn.init(jax.random.key(1), x)
    p = jax.tree_util.tree_map(jnp.asarray, vars_["params"])
    p["router"]["kernel"] = jnp.zeros_like(p["router"]["kernel"])
    p["router"]["bias"] = jnp.zeros_like(p["router"]["bias"])
    _, mutated = ffn.apply({"params": p}, x, mutable=["moe_metrics"])
    (dropped,) = mutated["moe_metrics"]["dropped_frac"]
    (load,) = mutated["moe_metrics"]["expert_load"]
    assert float(dropped) == pytest.approx(0.5)  # cap=16 of n=32 kept
    np.testing.assert_allclose(np.asarray(load), [1.0, 0.0])


def test_train_step_surfaces_routing_health():
    """The train step must carry the routing stats out as metrics:
    moe_dropped_frac / moe_load_max present, finite, and in-range for
    vit_moe (dense models' metric dicts don't grow these keys — pinned by
    every other step test's exact key-set assertions)."""
    mesh = parallel.make_mesh(4, 2, backend="tpu")
    model = models.get_model("vit_moe", depth=2)
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(model, jax.random.key(0), tx)
    sharding = parallel.state_shardings(mesh, state)
    state = parallel.place_tree(state, sharding)
    step = make_train_step(mesh, state_sharding=sharding)
    rng = np.random.default_rng(2)
    x = rng.integers(0, 255, (32, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 100, (32,), dtype=np.int32)
    bx, by = parallel.shard_batch((x, y), mesh)
    _, metrics = step(state, bx, by, jax.random.key(1))
    dropped = float(metrics["moe_dropped_frac"])
    load_max = float(metrics["moe_load_max"])
    assert 0.0 <= dropped < 1.0
    # max expert load lies in [1/E, 1]; a fresh router should not have
    # collapsed (load_max == 1.0 means every token on one expert)
    assert 1.0 / 8 <= load_max <= 1.0


def test_trainer_logs_moe_health_to_tensorboard(tmp_path):
    """fit() on vit_moe must write moe/dropped_frac and moe/load_max TB
    scalars (read back with tensorboard's own event reader) and a per-epoch
    'moe:' log line."""
    loader_mod = pytest.importorskip(
        "tensorboard.backend.event_processing.event_file_loader"
    )
    event_pb2 = pytest.importorskip("tensorboard.compat.proto.event_pb2")
    import glob

    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "128",
            "--model", "vit_moe",
            "--batch-size", "32", "--epoch", "1",
            "--ckpt-path", str(tmp_path),
        ],
    )
    trainer = Trainer(hp, model=models.get_model("vit_moe", depth=2))
    version = trainer.fit()
    trainer.close()
    vdir = tmp_path / f"version-{version}"
    f = glob.glob(str(vdir / "tb" / "events.out.tfevents.*"))[0]
    tags = {}
    # RawEventFileLoader + explicit parse, like test_tensorboard.py — the
    # cooked loader rewrites simple_value into tensor protos
    for raw in loader_mod.RawEventFileLoader(f).Load():
        e = event_pb2.Event()
        e.ParseFromString(raw)
        for v in e.summary.value:
            tags[v.tag] = v.simple_value
    assert 0.0 <= tags["moe/dropped_frac"] < 1.0
    assert 1.0 / 8 <= tags["moe/load_max"] <= 1.0
    assert "moe: " in (vdir / "experiment.log").read_text()


def test_trainer_rejects_gmm_under_expert_parallelism(tmp_path):
    """An explicit --moe-dispatch gmm with --model-parallel > 1 must be a
    clear config error (GSPMD can't partition the Pallas kernel over the
    expert axis); 'auto' quietly resolves to 'gather' instead."""
    argv = [
        "--synthetic-data", "--limit-examples", "256",
        "--model", "vit_moe",
        "--batch-size", "32", "--model-parallel", "2",
        "--moe-dispatch", "gmm",
        "--ckpt-path", str(tmp_path),
    ]
    with pytest.raises(ValueError, match="unsharded experts"):
        Trainer(load_config("tpu", argv=argv))
    auto_argv = [a for a in argv if a not in ("--moe-dispatch", "gmm")]
    hp = load_config("tpu", argv=auto_argv)
    assert hp.moe_dispatch == "auto"
    assert Trainer(hp).model.moe_dispatch == "gather"


def test_trainer_rejects_moe_with_pipeline_style(tmp_path):
    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "256",
            "--model", "vit_moe",
            "--batch-size", "32", "--model-parallel", "2",
            "--parallel-style", "pipeline",
            "--ckpt-path", str(tmp_path),
        ],
    )
    with pytest.raises(ValueError, match="does not support MoE"):
        Trainer(hp)


def test_resolve_dispatch_sharding_aware():
    """Construction-time EP resolution is shared by every get_model caller
    (ADVICE r5 #1): 'auto' falls back to the partitionable 'gather', an
    explicit 'gmm' is rejected, and without EP 'auto' passes through to
    the call-time backend/VMEM resolution."""
    from distributed_training_comparison_tpu.models import resolve_dispatch

    assert resolve_dispatch("auto", expert_parallel=True) == "gather"
    assert resolve_dispatch("onehot", expert_parallel=True) == "onehot"
    assert resolve_dispatch("auto", expert_parallel=False) == "auto"
    with pytest.raises(ValueError, match="unsharded experts"):
        resolve_dispatch("gmm", expert_parallel=True)

    assert models.get_model("vit_moe", expert_parallel=True).moe_dispatch == "gather"
    assert models.get_model("vit_moe").moe_dispatch == "auto"
    with pytest.raises(ValueError, match="unsharded experts"):
        models.get_model("vit_moe", moe_dispatch="gmm", expert_parallel=True)


def test_auto_gmm_gate_respects_vmem_budget():
    """The call-time 'auto' resolution prices the gmm kernel's resident
    expert weights; over budget it composes via gather instead of handing
    Mosaic an uncompilable config (ADVICE r5 #2)."""
    from distributed_training_comparison_tpu.ops.vmem import (
        WEIGHT_BUDGET_BYTES,
        fits_weight_budget,
        gmm_weight_bytes,
    )

    # the shipped vit_moe config must keep its fast path
    assert fits_weight_budget(gmm_weight_bytes(8, 192, 768, jnp.bfloat16))
    # an LLM-scale expert bank must not
    big = gmm_weight_bytes(64, 1024, 4096, jnp.bfloat16)
    assert big > WEIGHT_BUDGET_BYTES
    assert not fits_weight_budget(big)
