"""Multi-process serve fleet tests (ISSUE 17): the process-per-replica
transport and the queueing-aware autoscaler, plus the satellites.

The load-bearing properties pinned here:

- **Wire protocol** (`serve/fleet/transport.py`): a frame round-trips
  header + ndarray body bitwise; torn and oversized frames fail typed
  (``FleetTransportError``), never hang; the per-replica request and
  exporter ports are deterministic and disjoint (the satellite fix for
  N processes colliding on one ``--metrics-port``).
- **Client contract**: a worker-relayed engine error surfaces as a
  typed ``RuntimeError`` (batch fails, replica lives); a vanished peer
  surfaces as ``FleetTransportError`` (batch requeues, supervisor
  relaunches) — the dispatcher's two recovery paths fork on exactly
  this distinction.
- **Autoscaler math** (`serve/fleet/autoscale.py`), in isolation from
  any fleet: the G/G/m fit sizes to the smallest m meeting every p99
  target, degrades explicitly (utilization rule on thin reservoirs,
  hold on no data), and the control loop's hysteresis — immediate up,
  reluctant down, cooldown between applies — is clock-driven and
  deterministic under a fake clock.
- **`scale_serve` autopilot action**: parses, stays dry-run by
  default, spends the policy budget, and is honestly ``unbound``
  without an autoscaler.
- **Requeue-on-death** (`ClassQueue.requeue`): undispatched entries
  return to the FRONT of their lanes (age order preserved), resolved
  futures are skipped, a closed queue fails them typed — a replica
  crash costs latency, not requests.
- **Thread-transport twins**: every fleet-resize behavior
  (``scale_to`` / ``scale_down`` LIFO / ``active_replicas`` / the live
  ticker driving the autoscaler) runs fast in tier-1 against stub
  engines; the REAL process spawn e2e (worker handshake, socket serve,
  kill-mid-stream requeue + supervisor restart) is slow-marked.
"""

import json
import os
import signal
import socket
import struct
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))

import run_report  # noqa: E402

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.ops import policy as P
from distributed_training_comparison_tpu.serve import (
    ClassQueue,
    ServeMetrics,
    ServeRouter,
    fold_seed,
    request_pool,
)
from distributed_training_comparison_tpu.serve.batcher import BatcherClosed
from distributed_training_comparison_tpu.serve.fleet import (
    Autoscaler,
    FleetTransportError,
    ProcessReplica,
    ReplicaClient,
    decode_array,
    encode_array,
    parse_scale_targets,
    read_handshake,
    recv_msg,
    render_worker_env,
    replica_metrics_port,
    replica_port,
    send_msg,
    size_for_targets,
    worker_hparams_dict,
    wq_ggm,
)
from distributed_training_comparison_tpu.serve.router import READY, STOPPED

from test_policy import FakeBus, _alert
from test_serve_fleet import _StubEngine, _bus, _img, _wait


# ----------------------------------------------------------- the protocol


def test_frame_roundtrip_carries_arrays_bitwise():
    a, b = socket.socketpair()
    try:
        imgs = np.random.default_rng(0).integers(
            0, 256, size=(3, 8, 8, 3), dtype=np.uint8
        )
        meta, body = encode_array(imgs)
        send_msg(a, {"op": "submit", "tag": 7, **meta}, body)
        header, rbody = recv_msg(b)
        assert header["op"] == "submit" and header["tag"] == 7
        out = decode_array(header, rbody)
        assert out.dtype == np.uint8 and np.array_equal(out, imgs)
        # a body-less control frame rides the same framing
        send_msg(b, {"op": "health"})
        header2, rbody2 = recv_msg(a)
        assert header2 == {"op": "health"} and rbody2 == b""
    finally:
        a.close()
        b.close()


def test_torn_and_oversized_frames_fail_typed():
    a, b = socket.socketpair()
    try:
        # oversized: a length prefix past MAX_FRAME is a protocol error,
        # not a big batch the receiver should try to allocate
        a.sendall(struct.pack("!II", 1 << 31, 0))
        with pytest.raises(FleetTransportError):
            recv_msg(b)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        # torn: peer vanishes mid-message
        a.sendall(struct.pack("!II", 100, 0) + b'{"op":')
        a.close()
        with pytest.raises(FleetTransportError):
            recv_msg(b)
    finally:
        b.close()
    # a mis-shaped body never silently reshapes
    with pytest.raises(FleetTransportError):
        decode_array({"shape": [2, 4], "dtype": "float32"}, b"\x00" * 12)


def test_replica_ports_are_deterministic_and_disjoint():
    # request ports: base + rid; base 0 = bind-ephemeral (handshake file
    # reports the real port)
    assert [replica_port(9000, r) for r in range(4)] == [
        9000, 9001, 9002, 9003,
    ]
    assert replica_port(0, 5) == 0
    # exporter ports: the router keeps base+0, replica r takes base+1+r —
    # the satellite fix for N processes colliding on one --metrics-port
    ports = {replica_metrics_port(9100, r) for r in range(4)}
    assert ports == {9101, 9102, 9103, 9104}
    assert 9100 not in ports
    assert replica_metrics_port(0, 2) == 0  # exporter off stays off
    # request and exporter ranges for one base pair never overlap
    assert not ports & {replica_port(9000, r) for r in range(4)}


def test_render_worker_env_pins_platform_and_device_slice():
    base = {"PATH": "/bin", "JAX_PLATFORMS": "tpu"}
    env = render_worker_env(base, 1, platform="cpu")
    assert env["JAX_PLATFORMS"] == "cpu" and env["PATH"] == "/bin"
    assert base["JAX_PLATFORMS"] == "tpu"  # caller's env untouched
    tpu = render_worker_env({}, 0, platform="tpu", visible_devices=[2, 3])
    assert tpu["TPU_VISIBLE_CHIPS"] == "2,3"
    gpu = render_worker_env({}, 0, platform="cuda", visible_devices=[1])
    assert gpu["CUDA_VISIBLE_DEVICES"] == "1"


def test_replica_client_forks_engine_errors_from_transport_loss():
    """The dispatcher's two recovery paths hinge on the client's error
    types: an engine error relayed by a LIVE worker is RuntimeError
    (fail the batch, keep the replica); a vanished worker is
    FleetTransportError (requeue the batch, relaunch the worker)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    hits = []

    def worker():
        conn, _ = srv.accept()
        with conn:
            # 1st submit: echo logits; 2nd: relay an engine error;
            # then vanish without a reply
            header, body = recv_msg(conn)
            imgs = decode_array(header, body)
            meta, rbody = encode_array(
                np.ones((imgs.shape[0], 4), np.float32)
            )
            send_msg(conn, {"op": "result", **meta}, rbody)
            recv_msg(conn)
            send_msg(conn, {
                "op": "error", "etype": "ValueError", "error": "boom",
            })
            recv_msg(conn)
            hits.append("gone")

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        client = ReplicaClient(port, connect_timeout_s=2.0)
        out = client.submit_batch(np.zeros((2, 4, 4, 3), np.uint8))
        assert out.shape == (2, 4)
        with pytest.raises(RuntimeError, match="ValueError: boom"):
            client.submit_batch(np.zeros((1, 4, 4, 3), np.uint8))
        with pytest.raises(FleetTransportError):
            client.submit_batch(np.zeros((1, 4, 4, 3), np.uint8))
        client.close()
    finally:
        srv.close()
        t.join(timeout=5)
    # nobody listening at all is the same typed failure, at connect
    with pytest.raises(FleetTransportError):
        ReplicaClient(port, connect_timeout_s=0.5)


# ------------------------------------------- satellite: seed decorrelation


def test_fold_seed_decorrelates_pools_deterministically():
    assert fold_seed(7, "serve", 0) == fold_seed(7, "serve", 0)
    assert fold_seed(7, "serve", 0) != fold_seed(7, "serve", 1)
    assert fold_seed(7, "serve", 0) != fold_seed(8, "serve", 0)
    base = request_pool(4, image_size=8, seed=5)
    folded = request_pool(4, image_size=8, seed=5, fold=("leg", 1))
    again = request_pool(4, image_size=8, seed=5, fold=("leg", 1))
    assert base.shape == folded.shape
    assert not np.array_equal(base, folded)  # legs stop replaying one stream
    assert np.array_equal(folded, again)  # but each leg is reproducible


# ------------------------------------------------------- autoscaler math


def test_parse_scale_targets_grammar():
    assert parse_scale_targets("p99=250") == {"*": 0.25}
    assert parse_scale_targets("gold:p99=150,p99=400") == {
        "gold": 0.15, "*": 0.4,
    }
    for bad in ("p98=300", "gold:p99=-5", "x", "", "p99="):
        with pytest.raises(ValueError):
            parse_scale_targets(bad)


def test_wq_ggm_sanity_and_saturation():
    assert wq_ggm(0.0, 0.1, 1) == 0.0  # no arrivals, no queue
    assert wq_ggm(20.0, 0.1, 1) == float("inf")  # rho >= 1: saturated
    w1 = wq_ggm(5.0, 0.1, 1)
    assert w1 == pytest.approx(0.05)  # rho=.5: rho^2/(1-rho) * S
    # more servers always shorten the wait; saturation clears at m=2
    assert wq_ggm(5.0, 0.1, 2) < w1
    assert wq_ggm(20.0, 0.1, 3) < float("inf")
    # burstier arrivals (ca2 > 1) lengthen it
    assert wq_ggm(5.0, 0.1, 1, ca2=4.0) > w1


_SVC = {"n": 100, "mean_s": 0.1, "cv2": 0.5, "p99_s": 0.15, "mean_batch": 2.0}


def test_size_for_targets_smallest_m_meeting_every_target():
    m, sized_by, rows = size_for_targets(30.0, _SVC, {"*": 0.4})
    assert (m, sized_by) == (3, "ggm")
    # the returned m meets the bound; m-1 provably violates it
    for row in rows:
        assert row["m"] == 3 and row["predicted_p99_ms"] <= 400.0
    from distributed_training_comparison_tpu.serve.fleet.autoscale import (
        predicted_p99_s,
    )
    assert predicted_p99_s(30.0, _SVC, 2) > 0.4
    # an unmeetable target caps at max_replicas rather than looping
    m_cap, by_cap, _ = size_for_targets(30.0, _SVC, {"*": 0.001},
                                        max_replicas=4)
    assert (m_cap, by_cap) == (4, "ggm")


def test_size_for_targets_degrades_explicitly():
    # a thin reservoir (< MIN_TAIL_SAMPLES) has no tail to fit: the
    # PR-14 utilization rule on the measured mean, honestly labeled
    thin = dict(_SVC, n=10)
    m, sized_by, _ = size_for_targets(30.0, thin, {"*": 0.4})
    assert sized_by == "utilization"
    assert m == 3  # ceil(15 batches/s * 0.1s / 0.7)
    # no data at all: hold at the floor, labeled no-data
    m0, by0, _ = size_for_targets(30.0, dict(_SVC, n=2), {"*": 0.4})
    assert (m0, by0) == (1, "no-data")
    m0, by0, _ = size_for_targets(0.0, _SVC, {"*": 0.4})
    assert by0 == "no-data"


class _ScaleMetrics:
    """Autoscaler-facing metrics stub with twistable load."""

    classes = None

    def __init__(self, lam=30.0, svc=None):
        self.lam = lam
        self.svc = dict(svc or _SVC)

    def arrival_stats(self, window_s=30.0, cls=None):
        return {"n": 100, "lam_rps": self.lam, "ca2": 1.0}

    def service_stats(self):
        return dict(self.svc)


class _ScaleRouter:
    """Router stand-in: just the resize surface the autoscaler drives."""

    def __init__(self, n=1):
        self.n = n
        self.calls = []

    def active_replicas(self):
        return self.n

    def scale_to(self, m):
        self.calls.append(m)
        added = list(range(self.n, m))
        drained = list(range(m, self.n))
        self.n = m
        return {"added": added, "drained": drained}


def test_autoscaler_scale_up_is_immediate_and_emitted():
    fb, clk = FakeBus(), [0.0]
    metrics, router = _ScaleMetrics(lam=30.0), _ScaleRouter(n=1)
    a = Autoscaler(metrics, {"*": 0.4}, bus=fb, clock=lambda: clk[0])
    d = a.step(router)
    assert d["state"] == "applied" and d["proposed"] == 3
    assert router.n == 3 and d["added"] == [1, 2]
    assert fb.states("serve_scale") == ["decision", "applied"]


def test_autoscaler_cooldown_then_scale_down_hysteresis():
    fb, clk = FakeBus(), [0.0]
    metrics, router = _ScaleMetrics(lam=30.0), _ScaleRouter(n=1)
    a = Autoscaler(
        metrics, {"*": 0.4}, bus=fb, clock=lambda: clk[0],
        cooldown_s=15.0, hold=2,
    )
    assert a.step(router)["state"] == "applied"  # up to 3, arms cooldown
    metrics.lam = 0.5  # the flash crowd ends: the math now wants m=1
    d = a.step(router)
    assert d["state"] == "hold" and "cooldown" in d["reason"]
    assert router.n == 3  # nothing moved
    clk[0] = 16.0  # cooldown passed: hysteresis takes over
    d = a.step(router)
    assert d["state"] == "hold" and d["streak"] == 1
    clk[0] = 17.0
    d = a.step(router)  # second consecutive down-vote + headroom clears
    assert d["state"] == "applied" and router.n == 1
    assert d["drained"] == [1, 2]
    # the event trail shows the reluctance: hold, hold, then the apply
    assert fb.states("serve_scale") == [
        "decision", "applied", "hold", "hold", "decision", "applied",
    ]


def test_autoscaler_no_data_holds_silently():
    fb = FakeBus()
    a = Autoscaler(
        _ScaleMetrics(lam=30.0, svc=dict(_SVC, n=0)), {"*": 0.4}, bus=fb,
        clock=lambda: 0.0,
    )
    router = _ScaleRouter(n=2)
    d = a.step(router)
    assert d["state"] == "steady" and d["sized_by"] == "no-data"
    assert d["proposed"] == 2 and router.n == 2
    assert fb.states("serve_scale") == []  # steady ticks don't spam the bus


def test_autoscaler_force_bypasses_hysteresis_not_math():
    fb = FakeBus()
    a = Autoscaler(
        _ScaleMetrics(lam=0.5), {"*": 0.4}, bus=fb, clock=lambda: 0.0,
        cooldown_s=1000.0, hold=5,
    )
    router = _ScaleRouter(n=3)
    d = a.step(router, force=True)  # scale_serve's path
    assert d["state"] == "applied" and d["forced"] and router.n == 1


# ------------------------------------ scale_serve via the policy engine


def test_scale_serve_action_through_the_policy_engine():
    fb = FakeBus()
    metrics, router = _ScaleMetrics(lam=30.0), _ScaleRouter(n=1)
    a = Autoscaler(metrics, {"*": 0.4}, clock=lambda: 0.0)
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> scale_serve:cooldown=0"]),
        bus=fb, mode="act", clock=lambda: 1e9,
    )
    eng.bind_actions(P.serve_actions(router, a))
    eng.observe_event(_alert())
    assert fb.states() == ["requested", "completed"]
    done = [e for e in fb.events
            if e["payload"].get("state") == "completed"][0]["payload"]
    # the completed event carries WHAT the forced step decided
    assert done["proposed"] == 3 and done["sized_by"] == "ggm"
    assert done["scale_state"] == "applied"
    assert router.n == 3 and a.applied == 1


def test_scale_serve_dry_run_default_and_budget():
    fb = FakeBus()
    router = _ScaleRouter(n=1)
    a = Autoscaler(_ScaleMetrics(lam=30.0), {"*": 0.4}, clock=lambda: 0.0)
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> scale_serve:cooldown=0"]),
        bus=fb, mode="dry-run", clock=lambda: 1e9,
    )
    eng.bind_actions(P.serve_actions(router, a))
    eng.observe_event(_alert())
    assert fb.states() == ["dry_run"]
    assert router.n == 1 and a.applied == 0  # provably took no action
    # act mode: the per-attempt budget bounds an alert storm
    fb2 = FakeBus()
    eng2 = P.PolicyEngine(
        P.parse_policy_specs(
            ["a -> scale_serve:cooldown=0", "b -> scale_serve:cooldown=0"]
        ),
        bus=fb2, mode="act", max_actions=1, clock=lambda: 1e9,
    )
    eng2.bind_actions(P.serve_actions(router, a))
    eng2.observe_event(_alert(metric="a"))
    eng2.observe_event(_alert(metric="b"))
    assert fb2.states() == ["requested", "completed", "budget"]


def test_scale_serve_unbound_without_autoscaler():
    """No --serve-scale-target, no autoscaler: a rule naming
    scale_serve records `unbound` instead of half-acting."""
    fb = FakeBus()
    actions = P.serve_actions(_ScaleRouter(n=1))  # no autoscaler
    assert "scale_serve" not in actions
    eng = P.PolicyEngine(
        P.parse_policy_specs(["m -> scale_serve"]),
        bus=fb, mode="act", clock=lambda: 1e9,
    )
    eng.bind_actions(actions)
    eng.observe_event(_alert())
    assert fb.states() == ["unbound"]


# --------------------------------------------- requeue: crash ≠ loss


def test_requeue_returns_entries_to_lane_front_in_age_order():
    q = ClassQueue(limit=16)
    fa, fb_ = q.submit(_img()), q.submit(_img())
    batch = q.take(2, continuous=True)
    assert [f for _, f in batch] == [fa, fb_]
    fc = q.submit(_img())  # admitted after the doomed dispatch
    assert q.requeue(batch) == 2
    # age preserved: the requeued pair dispatches BEFORE the newcomer
    nxt = q.take(8, continuous=True)
    assert [f for _, f in nxt] == [fa, fb_, fc]
    q.close(drain=False)


def test_requeue_skips_resolved_futures():
    q = ClassQueue(limit=16)
    fa, fb_ = q.submit(_img()), q.submit(_img())
    batch = q.take(2, continuous=True)
    fa.set_result(np.zeros(4, np.float32))  # resolved meanwhile
    assert q.requeue(batch) == 1
    nxt = q.take(8, continuous=True)
    assert [f for _, f in nxt] == [fb_]
    q.close(drain=False)


def test_requeue_on_closed_queue_fails_typed():
    m = ServeMetrics()
    q = ClassQueue(limit=16, metrics=m)
    fut = q.submit(_img())
    batch = q.take(2, continuous=True)
    q.close(drain=False)
    assert q.requeue(batch) == 0
    with pytest.raises(BatcherClosed):
        fut.result(timeout=1)
    assert m.failed == 1  # lands in the SLO denominator


# ------------------------------------------- the sketches the sizer fits


def test_arrival_sketch_counts_admissions_per_class():
    m = ServeMetrics()
    q = ClassQueue(limit=64, metrics=m)
    for _ in range(5):
        q.submit(_img())
    st = m.arrival_stats(window_s=60.0)
    assert st["n"] == 5 and st["lam_rps"] > 0 and st["ca2"] >= 0.0
    # per-class sketches are separate
    assert m.arrival_stats(window_s=60.0, cls="default")["n"] == 5
    assert m.arrival_stats(window_s=60.0, cls="gold")["n"] == 0
    q.close(drain=False)


def test_arrival_sketch_excludes_sheds():
    """Sheds are deliberately not arrivals-for-sizing: sizing to shed
    traffic would chase load the queue already refused."""
    m = ServeMetrics()
    q = ClassQueue(limit=1, metrics=m)
    q.submit(_img())
    from distributed_training_comparison_tpu.serve import QueueOverflow
    with pytest.raises(QueueOverflow):
        q.submit(_img())
    assert m.arrival_stats(window_s=60.0)["n"] == 1  # only the admission
    q.close(drain=False)


def test_service_sketch_welford_mean_cv_and_batch():
    m = ServeMetrics()
    assert m.service_stats() == {
        "n": 0, "mean_s": 0.0, "cv2": 1.0, "p99_s": 0.0, "mean_batch": 1.0,
    }
    for _ in range(10):
        m.record_service(0.1, 2)
    st = m.service_stats()
    assert st["n"] == 10
    assert st["mean_s"] == pytest.approx(0.1)
    assert st["cv2"] == pytest.approx(0.0, abs=1e-9)
    assert st["p99_s"] == pytest.approx(0.1)
    assert st["mean_batch"] == pytest.approx(2.0)
    m.record_service(0.3, 4)  # variance and batch mix move
    st = m.service_stats()
    assert st["cv2"] > 0 and st["mean_batch"] == pytest.approx(24 / 11)


def test_dispatch_feeds_the_service_sketch():
    """The thread path's dispatch_batch times the engine and records
    one service sample per dispatch — the sketch fills itself."""
    m = ServeMetrics()
    q = ClassQueue(limit=16, metrics=m)
    from distributed_training_comparison_tpu.serve.batcher import (
        dispatch_batch,
    )
    eng = _StubEngine(delay_s=0.01)
    futs = [q.submit(_img()) for _ in range(3)]
    done = dispatch_batch(eng, q.take(8, continuous=True), m)
    assert len(done) == 3 and all(f.done() for f in futs)
    st = m.service_stats()
    assert st["n"] == 1 and st["mean_s"] >= 0.01
    assert st["mean_batch"] == pytest.approx(3.0)
    q.close(drain=False)


# ------------------------------------- thread-transport twins (tier-1)


def test_router_scale_to_grows_and_shrinks_lifo(tmp_path):
    stubs = {}

    def factory(rid):
        stubs[rid] = _StubEngine(rid=rid)
        return stubs[rid]

    bus = _bus(tmp_path)
    r = ServeRouter(factory, replicas=1, bus=bus, queue_limit=64,
                    emit_every_s=0.2)
    try:
        r.warmup()
        assert r.active_replicas() == 1
        res = r.scale_to(3)
        assert res == {"added": [1, 2], "drained": []}
        _wait(lambda: r.active_replicas() == 3, what="scale-up to 3")
        _wait(lambda: all(x.state == READY for x in r.replicas),
              what="new replicas ready")
        # shrink retires the NEWEST capacity first (LIFO): the original
        # fleet stays stable
        res = r.scale_to(1)
        assert res == {"added": [], "drained": [2, 1]}
        _wait(lambda: r.active_replicas() == 1, what="scale-down to 1")
        assert r.replicas[0].state == READY
        # the survivor still serves
        assert r.submit(_img()).result(timeout=10).shape == (4,)
        assert r.scale_to(1) == {"added": [], "drained": []}
    finally:
        r.close()
    # both directions left replica lifecycle events behind
    states = {
        (e["payload"]["replica"], e["payload"]["state"])
        for e in obs.load_events(Path(tmp_path) / "events.jsonl")
        if e["kind"] == "replica" and "state" in e.get("payload", {})
    }
    assert (2, "ready") in states and (2, "stopped") in states


def test_router_ticker_drives_the_autoscaler_live(tmp_path):
    """The live loop twin: an attached autoscaler, stepped by the
    router's own ticker, grows the fleet without anyone calling step."""
    bus = _bus(tmp_path)
    r = ServeRouter(
        lambda rid: _StubEngine(rid=rid), replicas=1, bus=bus,
        queue_limit=64, emit_every_s=0.05,
    )
    r._scale_every_s = 0.05
    a = Autoscaler(_ScaleMetrics(lam=30.0), {"*": 0.4}, bus=bus,
                   cooldown_s=0.0, max_replicas=3)
    r.attach_autoscaler(a)
    try:
        r.warmup()
        # wait on the COUNTER, not active_replicas(): the replicas go
        # active inside scale_to, a beat before step() bumps `applied`
        _wait(lambda: a.applied >= 1 and r.active_replicas() == 3,
              what="live scale-up")
    finally:
        r.close()
    evs = obs.load_events(Path(tmp_path) / "events.jsonl")
    applied = [e for e in evs if e["kind"] == "serve_scale"
               and e["payload"]["state"] == "applied"]
    assert applied and applied[0]["payload"]["added"]


def test_thread_replica_stops_with_per_class_latency_payload(tmp_path):
    bus = _bus(tmp_path)
    r = ServeRouter(lambda rid: _StubEngine(rid=rid), replicas=1, bus=bus,
                    queue_limit=16)
    try:
        r.warmup()
        for f in [r.submit(_img()) for _ in range(4)]:
            f.result(timeout=10)
    finally:
        r.close()
    stops = [
        e["payload"] for e in obs.load_events(Path(tmp_path) / "events.jsonl")
        if e["kind"] == "replica" and e["payload"].get("state") == "stopped"
    ]
    assert stops and stops[0]["transport"] == "thread"
    classes = stops[0]["classes"]
    assert classes["default"]["n"] == 4
    assert classes["default"]["p99_ms"] >= 0.0


# --------------------------------------- run_report --serve (satellite)


def test_serve_replica_table_merges_lifecycle(tmp_path):
    bus = _bus(tmp_path)
    bus.emit("replica", replica=0, state="ready", transport="process",
             pid=4242, port=9001)
    bus.emit("replica", replica=0, beat=True, dispatches=6, routed=12,
             transport="process")
    bus.emit("replica", replica=0, state="starting", transport="process",
             restart=1, requeued=4)
    bus.emit("replica", replica=0, state="stopped", transport="process",
             dispatches=9, routed=18,
             classes={"default": {"n": 18, "p99_ms": 12.5}})
    table = run_report.serve_replica_table(
        obs.load_events(Path(tmp_path) / "events.jsonl")
    )
    row = table["0"]
    assert row["transport"] == "process" and row["pid"] == 4242
    assert row["dispatches"] == 9 and row["routed"] == 18  # max, not last
    assert row["restarts"] == 1
    assert row["state"] == "stopped"
    assert row["classes"]["default"]["p99_ms"] == 12.5
    # beats never count as lifecycle transitions
    assert row["drains"] == 0 and row["deaths"] == 0


def test_serve_report_gates_on_scale_fleet_disagreement(tmp_path, capsys):
    """An APPLIED scale decision whose added replica never went ready is
    an autoscaler/fleet disagreement worth an exit 1."""
    ok_dir, bad_dir = tmp_path / "ok", tmp_path / "bad"
    for d, honored in ((ok_dir, True), (bad_dir, False)):
        bus = obs.EventBus(run_id="e" * 16)
        bus.bind_dir(d)
        bus.emit("serve_route", state="routing", classes={
            "default": {"completed": 4, "ok_deadline": 4, "expired": 0,
                        "shed": 0, "priority": 1, "deadline_ms": None,
                        "target": 0.0},
        })
        bus.emit("replica", replica=0, state="ready", transport="process")
        bus.emit("serve_scale", state="applied", current=1, proposed=2,
                 added=[1], drained=[])
        if honored:
            bus.emit("replica", replica=1, state="ready",
                     transport="process")
    assert run_report.serve_scale_mismatches(
        obs.load_events(Path(ok_dir) / "events.jsonl")
    ) == []
    assert run_report.serve_report(ok_dir) == 0
    capsys.readouterr()
    assert run_report.serve_report(bad_dir) == 1
    out = capsys.readouterr().out
    assert "SCALE MISMATCH" in out and "never went ready" in out


# ----------------------------------------------- flags + event registry


def test_fleet_flags_parse_and_validate():
    hp = load_config("tpu", argv=[
        "--serve", "--serve-transport", "process",
        "--serve-scale-target", "gold:p99=150,p99=400",
        "--serve-port-base", "9000", "--serve-max-replicas", "4",
        "--serve-classes", "gold:priority=0:deadline_ms=250",
    ])
    assert hp.serve_transport == "process"
    assert hp.serve_scale_target == "gold:p99=150,p99=400"
    assert hp.serve_port_base == 9000 and hp.serve_max_replicas == 4
    with pytest.raises(SystemExit):  # malformed target dies at the CLI
        load_config("tpu", argv=["--serve-scale-target", "p98=300"])
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--serve-scale-target", "gold:p99=-5"])
    with pytest.raises(SystemExit):  # port base out of range
        load_config("tpu", argv=["--serve-port-base", "70000"])
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--serve-max-replicas", "0"])
    with pytest.raises(SystemExit):  # unknown transport
        load_config("tpu", argv=["--serve-transport", "carrier-pigeon"])


def test_serve_scale_kind_is_registered():
    from distributed_training_comparison_tpu.serve.fleet.autoscale import (
        SCALE_KIND,
    )
    assert SCALE_KIND == "serve_scale"
    assert "serve_scale" in obs.KNOWN_KINDS
    assert "replica" in obs.KNOWN_KINDS


def test_serve_replica_kill_scenario_is_registered():
    from distributed_training_comparison_tpu.resilience import (
        CHAOS_SCENARIOS,
        check_chaos_expectations,
    )

    sc = CHAOS_SCENARIOS["serve_replica_kill_flash"]
    assert sc["session"] == "serve"
    assert sc["driver"] == "kill_replica"
    assert "--serve-transport" in sc["extra_args"]
    # the expectation block is satisfiable by a green run...
    observed = {
        "final_rc": 0, "kills": 1, "restarts": 1,
        "failed_requests": 0, "p99_recovered": True,
    }
    assert check_chaos_expectations(sc["expect"], observed) == []
    # ...and actually binds on the zero-loss claim: a single failed
    # request (beyond shed/deadline accounting — there is none here)
    # must flunk the scenario
    assert check_chaos_expectations(
        sc["expect"], dict(observed, failed_requests=1)
    )
    assert check_chaos_expectations(
        sc["expect"], dict(observed, restarts=0)
    )


# ------------------------------------------ the REAL process fleet (slow)


def _process_spec(tmp_path, buckets=(1, 2), image_size=16):
    hp = load_config("single", argv=[
        "--model", "resnet18", "--image-size", str(image_size),
        "--serve-buckets", ",".join(str(b) for b in buckets),
        "--seed", "3", "--ckpt-path", str(tmp_path),
    ])
    return {
        "fleet_dir": str(tmp_path / "serve-fleet"),
        "events_dir": str(tmp_path),
        "hparams": worker_hparams_dict(hp),
        "port_base": 0,
        "metrics_port_base": 0,
        "platform": "cpu",
        "run_id": "f" * 16,
        "attempt": 0,
        "aot_dir": str(tmp_path / "aot"),
    }


@pytest.mark.slow
@pytest.mark.serve_fleet
def test_process_replica_end_to_end_serves_and_drains(tmp_path):
    """One REAL worker process: handshake file, socket serve, engine
    stats over RPC, orderly drain — the transport e2e at test size."""
    bus = _bus(tmp_path)
    spec = _process_spec(tmp_path)
    r = ServeRouter(
        None, replicas=1, transport="process", process_spec=spec,
        bus=bus, queue_limit=64, emit_every_s=0.5,
    )
    try:
        assert r.wait_ready(n=1, timeout=600)
        rep = r.replicas[0]
        assert isinstance(rep, ProcessReplica)
        assert rep.pid and rep.pid != os.getpid()
        hs = read_handshake(spec["fleet_dir"], 0)
        assert hs["state"] == "ready" and hs["pid"] == rep.pid
        # the worker engine compiled for the spec's image size, not the
        # stub fleet's 4px toy — submit at the size the worker serves
        img16 = np.zeros((16, 16, 3), np.uint8)
        futs = [r.submit(img16) for _ in range(8)]
        rows = [f.result(timeout=120) for f in futs]
        assert len(rows) == 8 and rows[0].shape[0] >= 2
    finally:
        r.close()
    _wait(lambda: r.replicas[0].state == STOPPED, timeout=30,
          what="clean drain")
    assert r.replicas[0].restarts == 0
    # the engine's stats crossed the RPC and folded into the router's
    st = r.replicas[0].engine_stats()
    assert st and st["compiles"] >= 1
    # the worker joined the run's event stream as process 1+rid
    worker_events = Path(tmp_path) / "events-p1.jsonl"
    assert worker_events.exists()
    kinds = {e["kind"] for e in obs.load_events(worker_events)}
    assert "replica" in kinds and "compile" in kinds


@pytest.mark.slow
@pytest.mark.serve_fleet
def test_process_replica_kill_requeues_and_supervisor_restarts(tmp_path):
    """SIGKILL the worker mid-stream: in-flight work requeues (zero
    failed requests), the supervisor relaunches inside its budget, and
    the relaunched worker — warm-started from the persisted AOT cache —
    finishes the backlog."""
    bus = _bus(tmp_path)
    spec = _process_spec(tmp_path, buckets=(1, 2), image_size=32)
    r = ServeRouter(
        None, replicas=1, transport="process", process_spec=spec,
        bus=bus, queue_limit=512, emit_every_s=0.5,
    )
    try:
        assert r.wait_ready(n=1, timeout=600)
        rep = r.replicas[0]
        pid = rep.pid
        img32 = np.zeros((32, 32, 3), np.uint8)
        futs = [r.submit(img32) for _ in range(200)]
        _wait(lambda: rep.dispatches >= 2, timeout=120,
              what="dispatches flowing")
        os.kill(pid, signal.SIGKILL)
        # every admitted request still completes: the killed dispatch
        # requeued, the backlog drained by the next incarnation
        rows = [f.result(timeout=600) for f in futs]
        assert len(rows) == 200
        _wait(lambda: rep.pid != pid and rep.state == READY, timeout=120,
              what="relaunched worker ready")
        assert rep.restarts >= 1
        assert r.metrics.failed == 0
    finally:
        r.close()
    evs = obs.load_events(Path(tmp_path) / "events.jsonl")
    lifecycle = [e["payload"] for e in evs if e["kind"] == "replica"]
    assert any(p.get("lifecycle") == "attempt_start" and p.get("attempt")
               for p in lifecycle), "supervisor restart never hit the bus"
