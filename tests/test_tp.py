"""Tensor parallelism: params are genuinely partitioned over the "model"
axis and the training math is unchanged by the layout.

The reference has no tensor parallelism (SURVEY.md §2.2); these tests guard
the beyond-parity capability: a (data, model) mesh where stage-3/4 convs and
the classifier head are channel-sharded (parallel/tp.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_comparison_tpu import models, parallel
from distributed_training_comparison_tpu.parallel.tp import (
    batch_stats_partition_specs,
    param_partition_specs,
    state_shardings,
)
from distributed_training_comparison_tpu.train import (
    configure_optimizers,
    create_train_state,
    make_train_step,
)

pytestmark = pytest.mark.slow  # multi-process / heavy-compile: full-suite only


class HP:
    lr = 0.1
    weight_decay = 1e-4
    lr_decay_step_size = 25
    lr_decay_gamma = 0.1


def _make_state(model_name="resnet18"):
    model = models.get_model(model_name)
    tx, _ = configure_optimizers(HP, steps_per_epoch=10)
    return create_train_state(model, jax.random.key(0), tx)


def _placed(mesh, state):
    sh = state_shardings(mesh, state)
    return parallel.place_tree(state, sh), sh


def test_param_specs_shard_tp_stages_only():
    state = _make_state()
    specs = param_partition_specs(state.params)
    # stage1/2 and stem fully replicated
    flat = jax.tree_util.tree_leaves(
        {k: v for k, v in specs.items() if not k.startswith(("stage3", "stage4", "head"))}
    )
    assert all(s == jax.sharding.PartitionSpec() for s in flat)
    # BasicBlock: Conv_0 column-parallel, Conv_1 row-parallel
    b0 = specs["stage3_block0"]
    assert b0["Conv_0"]["kernel"] == jax.sharding.PartitionSpec(None, None, None, "model")
    assert b0["Conv_1"]["kernel"] == jax.sharding.PartitionSpec(None, None, "model", None)
    assert b0["BatchNorm_0"]["scale"] == jax.sharding.PartitionSpec("model")
    assert b0["BatchNorm_1"]["scale"] == jax.sharding.PartitionSpec()
    # shortcut replicated
    assert b0["Conv_2"]["kernel"] == jax.sharding.PartitionSpec()
    # head column-parallel over classes
    assert specs["head"]["kernel"] == jax.sharding.PartitionSpec(None, "model")


def test_bottleneck_specs():
    state = _make_state("resnet50")
    specs = param_partition_specs(state.params)
    b0 = specs["stage3_block0"]
    # Bottleneck: Conv_1 (3x3) column-parallel, Conv_2 (1x1 expand) row-parallel
    assert b0["Conv_0"]["kernel"] == jax.sharding.PartitionSpec()
    assert b0["Conv_1"]["kernel"] == jax.sharding.PartitionSpec(None, None, None, "model")
    assert b0["Conv_2"]["kernel"] == jax.sharding.PartitionSpec(None, None, "model", None)
    assert b0["BatchNorm_1"]["scale"] == jax.sharding.PartitionSpec("model")
    # shortcut (Conv_3) replicated
    assert b0["Conv_3"]["kernel"] == jax.sharding.PartitionSpec()


def test_batch_stats_specs_follow_bn_params():
    state = _make_state()
    specs = batch_stats_partition_specs(state.params, state.batch_stats)
    assert specs["stage3_block0"]["BatchNorm_0"]["mean"] == jax.sharding.PartitionSpec(
        "model"
    )
    assert specs["stage3_block0"]["BatchNorm_1"]["var"] == jax.sharding.PartitionSpec()
    # top-level stem BN has bare mean/var leaves — replicated, no crash
    assert specs["stem_bn"]["mean"] == jax.sharding.PartitionSpec()


@pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2)])
def test_params_actually_partitioned(mesh_shape):
    mesh = parallel.make_mesh(8, mesh_shape[1], backend="tpu")
    assert dict(mesh.shape) == {
        "data": mesh_shape[0], "model": mesh_shape[1], "pipe": 1,
    }
    state = _make_state()
    placed, _ = _placed(mesh, state)

    k = placed.params["stage3_block0"]["Conv_0"]["kernel"]
    assert not k.sharding.is_fully_replicated
    shard_shapes = {s.data.shape for s in k.addressable_shards}
    assert shard_shapes == {(3, 3, 128, 256 // mesh_shape[1])}
    # distinct shards hold distinct data (it is a real partition, not copies)
    uniq = {np.asarray(s.data).tobytes() for s in k.addressable_shards}
    assert len(uniq) == mesh_shape[1]

    # momentum trace inherits the layout (suffix matching through opt_state):
    # the trace leaf for this conv kernel has its unique shape — assert it
    # carries the same partitioned sharding, not a replicated fallback
    trace_leaves = [
        x
        for x in jax.tree_util.tree_leaves(placed.opt_state)
        if getattr(x, "shape", None) == k.shape
    ]
    assert trace_leaves, "momentum trace leaf for stage3 conv not found"
    for t in trace_leaves:
        assert t.sharding == k.sharding

    head_kernel = placed.params["head"]["kernel"]
    assert not head_kernel.sharding.is_fully_replicated

    # replicated leaves stay replicated
    stem = placed.params["stem_conv"]["kernel"]
    assert stem.sharding.is_fully_replicated


def test_tp_training_matches_dp_trajectory():
    """Same data, same init: a (4,2) TP run must track the (8,1) DP run."""
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, size=(64, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 100, size=(64,), dtype=np.int32)

    losses = {}
    for mp in (1, 2):
        mesh = parallel.make_mesh(8, mp, backend="tpu")
        state = _make_state()
        placed, sh = _placed(mesh, state)
        step = make_train_step(
            mesh, precision="fp32", augment=False, state_sharding=sh
        )
        bx, by = parallel.shard_batch((images, labels), mesh)
        traj = []
        for i in range(3):
            placed, metrics = step(placed, bx, by, jax.random.key(7))
            traj.append(float(metrics["loss"]))
        losses[mp] = traj

    # step 0 matches to fp32 ulp; later steps drift as lr=0.1 SGD amplifies
    # partitioned-reduction ordering differences (observed ≤0.4% at step 3)
    np.testing.assert_allclose(losses[1][:1], losses[2][:1], rtol=1e-5)
    np.testing.assert_allclose(losses[1], losses[2], rtol=2e-2)


def test_vit_trunk_specs_megatron_layout():
    """ViT scanned trunk: q/k/v/mlp_up column-parallel, proj/mlp_down
    row-parallel, LayerNorms and biases-of-row layers replicated."""
    state = _make_state("vit_tiny")
    specs = param_partition_specs(state.params)
    b = specs["blocks"]
    for name in ("q_proj", "k_proj", "v_proj"):
        assert b[name]["kernel"] == jax.sharding.PartitionSpec(None, None, "model")
        assert b[name]["bias"] == jax.sharding.PartitionSpec(None, "model")
    assert b["proj"]["kernel"] == jax.sharding.PartitionSpec(None, "model", None)
    assert b["proj"]["bias"] == jax.sharding.PartitionSpec(None)
    assert b["mlp_up"]["kernel"] == jax.sharding.PartitionSpec(None, None, "model")
    assert b["mlp_down"]["kernel"] == jax.sharding.PartitionSpec(None, "model", None)
    assert b["ln_attn"]["scale"] == jax.sharding.PartitionSpec()
    # embed/pos/head outside the trunk
    assert specs["pos_emb"] == jax.sharding.PartitionSpec()
    assert specs["head"]["kernel"] == jax.sharding.PartitionSpec(None, "model")


def test_vit_tp_training_matches_dp_trajectory():
    """Same data, same init: ViT under (4,2) tensor parallelism tracks the
    (8,1) data-parallel trajectory (heads divide the model axis, so the
    q/k/v projection sharding is head-aligned)."""
    from distributed_training_comparison_tpu.models import ViT

    rng = np.random.default_rng(1)
    images = rng.integers(0, 255, size=(64, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 100, size=(64,), dtype=np.int32)
    model = ViT(depth=2, dim=64, heads=4, patch=8)
    tx, _ = configure_optimizers(HP, steps_per_epoch=10)

    losses = {}
    for mp in (1, 2):
        mesh = parallel.make_mesh(8, mp, backend="tpu")
        state = create_train_state(model, jax.random.key(0), tx)
        placed, sh = _placed(mesh, state)
        if mp == 2:
            assert not placed.params["blocks"]["q_proj"][
                "kernel"
            ].sharding.is_fully_replicated
        step = make_train_step(
            mesh, precision="fp32", augment=False, state_sharding=sh
        )
        bx, by = parallel.shard_batch((images, labels), mesh)
        traj = []
        for i in range(3):
            placed, metrics = step(placed, bx, by, jax.random.key(7))
            traj.append(float(metrics["loss"]))
        losses[mp] = traj

    np.testing.assert_allclose(losses[1][:1], losses[2][:1], rtol=1e-5)
    np.testing.assert_allclose(losses[1], losses[2], rtol=2e-2)
