"""Request tracing: context propagation, tail-based keep, the worker
span ring, and the ``run_report --trace`` merge.

What is pinned here and why:

- **Tail-keep decisions** — the whole value of the rail is that the
  traces an operator greps for (shed / expired / breached / requeued /
  errored) ALWAYS exist at sampling 0, and healthy requests cost only
  context stamps.  Each outcome is driven end-to-end through a real
  ``MicroBatcher`` and asserted against the emitted ``trace`` events.
- **One trace across a requeue** — the kill-requeue contract: the failed
  attempt's span names the dead replica with a ``requeued`` annotation,
  the retry names the survivor, one ``trace_id`` spans both.
- **The report merge** — ``--trace`` joins router span trees with worker
  device spans across event files, stars the widest p95 segment, skips
  torn records, and exits 1 exactly when a deadlined class breached with
  zero kept traces.
- **Satellites** — the worker ring's eager/flush/dedupe protocol, the
  fleet-dir flight rings reaching ``collect_black_box``, the autoscaler's
  measured-vs-modeled wait fields, and ``--diff``'s '-' (never 0) for
  absent segments.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import run_report  # noqa: E402

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.obs import (
    EventBus,
    MmapRing,
    RequestTracer,
    WorkerTraceRing,
    collect_black_box,
    find_rings,
    ring_filename,
)
from distributed_training_comparison_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    QueueOverflow,
    ServeFuture,
)

pytestmark = [pytest.mark.obs, pytest.mark.trace]


class _StubEngine:
    max_bucket = 8

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s

    def predict_logits(self, imgs):
        if self.delay_s:
            time.sleep(self.delay_s)
        return np.zeros((len(imgs), 4), np.float32)


def _img():
    return np.zeros((4, 4, 3), np.uint8)


def _trace_events(tmp_path, process_index=None):
    evs = []
    for f in run_report.find_event_files(tmp_path):
        evs.extend(obs.load_events(f))
    return [
        e for e in evs
        if e.get("kind") == "trace"
        and (process_index is None
             or e.get("process_index") == process_index)
    ]


# ------------------------------------------------------------ the tracer


def test_mint_is_seeded_and_sampling_deterministic():
    a = RequestTracer(sample_rate=0.5, seed=7)
    b = RequestTracer(sample_rate=0.5, seed=7)
    ca = [a.begin("default") for _ in range(32)]
    cb = [b.begin("default") for _ in range(32)]
    assert [c.trace_id for c in ca] == [c.trace_id for c in cb]
    assert [c.sampled for c in ca] == [c.sampled for c in cb]
    assert len({c.trace_id for c in ca}) == 32
    # a different seed decorrelates
    c = RequestTracer(sample_rate=0.5, seed=8)
    assert [x.sampled for x in (c.begin("default") for _ in range(32))] != [
        x.sampled for x in ca
    ]


def test_sample_rate_validated():
    with pytest.raises(ValueError):
        RequestTracer(sample_rate=1.5)
    with pytest.raises(ValueError):
        RequestTracer(sample_rate=-0.1)


def test_wire_header_rows_align_and_carry_keep_flags():
    tr = RequestTracer(sample_rate=0.0, seed=0)
    futs = []
    for keep in (False, True, None):
        fut = ServeFuture(time.monotonic(), None, cls="default")
        if keep is None:
            fut.trace = None  # an untraced request in a traced batch
        else:
            fut.trace = tr.begin("default")
            fut.trace.keep = keep
        futs.append(fut)
    batch = [(_img(), f) for f in futs]
    bsid = tr.batch_begin(batch, 3)
    hdr = tr.wire_header(batch, bsid, 3)
    assert hdr["batch"] == bsid
    assert len(hdr["reqs"]) == 3
    assert hdr["reqs"][0][1] == 0
    assert hdr["reqs"][1][1] == 1
    assert hdr["reqs"][2] is None
    # pending flush ids for this worker ride the same header, once
    tr.request_flush(3, "cafecafecafecafe")
    hdr2 = tr.wire_header(batch, bsid, 3)
    assert hdr2["flush"] == ["cafecafecafecafe"]
    assert "flush" not in tr.wire_header(batch, bsid, 3)
    # the header is what the frame codec will see: JSON-safe
    json.dumps(hdr2)


def test_finish_is_idempotent_first_outcome_wins():
    tr = RequestTracer(sample_rate=1.0, seed=0)
    ctx = tr.begin("default")
    tr.finish_ctx(ctx, "shed")
    tr.finish_ctx(ctx, "completed")
    assert tr.kept == 1
    assert tr.kept_by_reason == {"shed": 1}


# -------------------------------------------- tail-keep through a batcher


def test_healthy_requests_at_sample_zero_keep_nothing(tmp_path):
    bus = EventBus(run_id="a" * 16)
    bus.bind_dir(tmp_path)
    tr = RequestTracer(bus=bus, sample_rate=0.0, seed=0)
    with MicroBatcher(
        _StubEngine(), max_wait_ms=1, queue_limit=32, tracer=tr
    ) as b:
        futs = [b.submit(_img()) for _ in range(6)]
        for f in futs:
            f.result(timeout=5)
    bus.close()
    assert _trace_events(tmp_path) == []
    assert tr.dropped == 6 and tr.kept == 0


def test_sampled_trace_has_the_full_span_tree(tmp_path):
    bus = EventBus(run_id="b" * 16)
    bus.bind_dir(tmp_path)
    tr = RequestTracer(bus=bus, sample_rate=1.0, seed=0)
    with MicroBatcher(
        _StubEngine(delay_s=0.01), max_wait_ms=1, queue_limit=32, tracer=tr
    ) as b:
        b.submit(_img()).result(timeout=5)
    bus.close()
    (ev,) = _trace_events(tmp_path)
    p = ev["payload"]
    assert p["keep"] == "sampled" and p["outcome"] == "completed"
    names = [s["name"] for s in p["spans"]]
    for expected in ("request", "admit", "queue", "batch", "device",
                     "reply"):
        assert expected in names, f"missing span {expected} in {names}"
    # the thread transport measures the engine inline: device, no rpc
    assert "rpc" not in names
    dev = next(s for s in p["spans"] if s["name"] == "device")
    assert dev["dur_s"] >= 0.01


def test_shed_and_expired_and_breach_kept_at_sample_zero(tmp_path):
    bus = EventBus(run_id="c" * 16)
    bus.bind_dir(tmp_path)
    tr = RequestTracer(bus=bus, sample_rate=0.0, seed=0)
    eng = _StubEngine(delay_s=0.15)
    b = MicroBatcher(eng, max_wait_ms=1, queue_limit=4, tracer=tr)
    try:
        # breach: taken instantly from an empty queue, completes late
        breached = b.submit(_img(), deadline_ms=10.0)
        time.sleep(0.05)  # its batch is now in the engine
        # expired: dies in the queue behind the slow dispatch
        doomed = b.submit(_img(), deadline_ms=1.0)
        # shed: overflow the bounded queue behind the busy worker
        with pytest.raises(QueueOverflow):
            for _ in range(12):
                b.submit(_img())
        assert breached.result(timeout=5) is not None
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=5)
    finally:
        b.close()
    bus.close()
    reasons = {
        e["payload"]["keep"] for e in _trace_events(tmp_path)
    }
    assert "shed" in reasons
    assert "expired" in reasons
    assert "deadline_breach" in reasons
    breach_ev = next(
        e for e in _trace_events(tmp_path)
        if e["payload"]["keep"] == "deadline_breach"
    )
    assert breach_ev["payload"]["breach"] is True
    assert breach_ev["payload"]["outcome"] == "completed"


def test_requeued_request_keeps_one_trace_across_replicas():
    tr = RequestTracer(sample_rate=0.0, seed=0)
    emitted = []
    tr.bus = type("B", (), {"emit": lambda self, k, **p: emitted.append(p)})()
    fut = ServeFuture(time.monotonic(), None, cls="default")
    fut.trace = tr.begin("default")
    tr.enqueued(fut.trace)
    fut.trace.t_taken = time.monotonic()
    batch = [(_img(), fut)]
    # attempt 1 on replica 0 dies mid-dispatch
    b0 = tr.batch_begin(batch, 0)
    tr.batch_end(batch, b0, ok=False, requeued=True)
    tr.mark_requeued(fut)
    # attempt 2 on replica 1 succeeds
    b1 = tr.batch_begin(batch, 1)
    tr.batch_end(batch, b1)
    fut.set_result(np.zeros(4))
    tr.finish(fut, "completed")
    (p,) = emitted
    assert p["keep"] == "requeued" and p["requeues"] == 1
    rpcs = [s for s in p["spans"] if s["name"] == "rpc"]
    assert [s["rid"] for s in rpcs] == [0, 1]
    assert rpcs[0].get("requeued") is True and rpcs[0].get("ok") is False
    assert "requeued" not in rpcs[1] and "ok" not in rpcs[1]
    # both batch spans present, reply hangs off the surviving attempt
    assert [s["name"] for s in p["spans"]].count("batch") == 2
    reply = next(s for s in p["spans"] if s["name"] == "reply")
    assert reply["parent"] == b1


def test_failed_batch_keeps_trace_with_failed_reason(tmp_path):
    class _Broken(_StubEngine):
        def predict_logits(self, imgs):
            raise RuntimeError("engine on fire")

    bus = EventBus(run_id="d" * 16)
    bus.bind_dir(tmp_path)
    tr = RequestTracer(bus=bus, sample_rate=0.0, seed=0)
    with MicroBatcher(
        _Broken(), max_wait_ms=1, queue_limit=8, tracer=tr
    ) as b:
        fut = b.submit(_img())
        with pytest.raises(RuntimeError):
            fut.result(timeout=5)
    bus.close()
    (ev,) = _trace_events(tmp_path)
    assert ev["payload"]["keep"] == "failed"
    rpc = next(
        s for s in ev["payload"]["spans"]
        if s["name"] in ("rpc", "device")
    )
    assert rpc.get("ok") is False


def test_kept_traces_feed_the_wait_reservoir():
    tr = RequestTracer(sample_rate=1.0, seed=0)
    for wait in (0.01, 0.02, 0.03):
        ctx = tr.begin("default")
        ctx.t_enq = 100.0
        ctx.t_taken = 100.0 + wait
        tr.finish_ctx(ctx, "completed")
    stats = tr.queue_wait_stats()
    assert stats["n"] == 3
    assert 0.01 <= stats["p50"] <= 0.03
    assert abs(stats["mean"] - 0.02) < 1e-9


# ------------------------------------------------------- the worker ring


class _RecBus:
    def __init__(self):
        self.events = []

    def emit(self, kind, **payload):
        self.events.append((kind, payload))


def test_worker_ring_eager_emit_then_flush_dedupes():
    bus = _RecBus()
    ring = WorkerTraceRing(bus, replica=2, slots=8)
    hdr = {"reqs": [["aaaa", 0], ["bbbb", 1], None], "batch": "b1"}
    ring.record(hdr, t0_wall=123.0, dur_s=0.05, n=3)
    # keep-now row emitted eagerly, the tail-pending one buffered
    assert len(bus.events) == 1
    kind, p = bus.events[0]
    assert kind == "trace" and p["trace_ids"] == ["bbbb"]
    assert p["span"]["rid"] == 2 and p["span"]["batch"] == "b1"
    # retro-flush emits the buffered id once; the eager one never again
    assert ring.flush(["aaaa", "bbbb"]) == 1
    assert bus.events[1][1]["trace_ids"] == ["aaaa"]
    assert ring.flush(["aaaa", "bbbb"]) == 0


def test_worker_ring_flush_rides_the_next_submit_header():
    bus = _RecBus()
    ring = WorkerTraceRing(bus, replica=0, slots=8)
    ring.record({"reqs": [["t1", 0]], "batch": "b1"}, 1.0, 0.01, 1)
    assert bus.events == []  # nothing kept yet
    # the next frame piggybacks the router's tail-keep decision
    ring.record(
        {"reqs": [["t2", 0]], "batch": "b2", "flush": ["t1"]},
        2.0, 0.01, 1,
    )
    assert [p["trace_ids"] for _, p in bus.events] == [["t1"]]


def test_worker_ring_is_bounded():
    bus = _RecBus()
    ring = WorkerTraceRing(bus, replica=0, slots=4)
    for i in range(16):
        ring.record({"reqs": [[f"t{i}", 0]], "batch": f"b{i}"}, i, 0.01, 1)
    # only the newest 4 remain flushable
    assert ring.flush([f"t{i}" for i in range(16)]) == 4


# ----------------------------------------------- blackbox fleet-dir rings


def test_find_rings_includes_fleet_subdir_and_blackbox_collects(tmp_path):
    root = tmp_path
    fleet = root / "serve-fleet"
    # incarnation 1 of replica process 1+rid=2: the restart-safe name
    name = ring_filename(1, 2)
    assert name == "flight-a1-p2.ring"
    ring = MmapRing(fleet / name, slots=8)
    ring.append(json.dumps({
        "v": 1, "kind": "trace", "t_wall": 5.0, "t_mono": 1.0,
        "payload": {"trace_ids": ["dead"], "span": {"name": "device"}},
    }))
    ring.close()
    top = MmapRing(root / ring_filename(0, 0), slots=8)
    top.append(json.dumps({"v": 1, "kind": "run_start", "t_wall": 1.0}))
    top.close()
    found = find_rings(root)
    assert fleet / name in found and root / "flight.ring" in found
    out = collect_black_box(root)
    report = json.loads(Path(out).read_text())
    rel = f"serve-fleet/{name}"
    assert rel in report["rings"]
    assert report["rings"][rel]["last_kinds"] == ["trace"]
    # the dead worker's final emits are in the merged timeline
    assert any(e.get("kind") == "trace" for e in report["events"])


# --------------------------------------------------- autoscaler wait rows


class _FlatMetrics:
    classes = {"default": None}

    def arrival_stats(self, window_s):
        return {"lam_rps": 10.0, "ca2": 1.0}

    def service_stats(self):
        return {
            "n": 64, "mean_s": 0.01, "cv2": 1.0, "p99_s": 0.02,
            "mean_batch": 2.0,
        }


def test_autoscaler_decision_carries_modeled_and_measured_wait():
    from distributed_training_comparison_tpu.serve.fleet.autoscale import (
        Autoscaler,
    )

    tr = RequestTracer(sample_rate=1.0, seed=0)
    ctx = tr.begin("default")
    ctx.t_enq, ctx.t_taken = 10.0, 10.25
    tr.finish_ctx(ctx, "completed")
    sc = Autoscaler(_FlatMetrics(), {"*": 0.4}, bus=None, tracer=tr)
    d = sc.evaluate(current=1)
    assert d["wait_modeled_s"] is not None and d["wait_modeled_s"] >= 0
    assert d["wait_measured_s"]["n"] == 1
    assert abs(d["wait_measured_s"]["p50"] - 0.25) < 1e-9
    # no tracer -> the field is honest about having no measurement
    d2 = Autoscaler(_FlatMetrics(), {"*": 0.4}, bus=None).evaluate(1)
    assert d2["wait_measured_s"] is None


# --------------------------------------------------- the --trace report


def _emit_synthetic_run(tmp_path, *, with_traces=True, breaches=2):
    """A run root with serve_route counters and (optionally) kept
    traces: router file at process 0, worker device spans at process 1."""
    router = EventBus(run_id="e" * 16, attempt=0, process_index=0)
    router.bind_dir(tmp_path)
    router.emit(
        "serve_route",
        router="r0",
        classes={
            "gold": {
                "priority": 0, "deadline_ms": 250.0, "target": 0.99,
                "completed": 5, "ok_deadline": 5 - breaches,
                "expired": 0, "shed": 0, "failed": 0,
            }
        },
    )
    if with_traces:
        for i in range(breaches):
            router.emit(
                "trace",
                trace_id=f"t{i}", cls="gold", keep="deadline_breach",
                sampled=False, outcome="completed", breach=True,
                requeues=0, deadline_ms=250.0,
                spans=[
                    {"name": "request", "span_id": "r", "parent": None,
                     "t0_wall": 100.0, "dur_s": 0.5},
                    {"name": "admit", "parent": "r", "t0_wall": 100.0,
                     "dur_s": 0.001},
                    {"name": "queue", "parent": "r", "t0_wall": 100.001,
                     "dur_s": 0.4},
                    {"name": "batch", "span_id": "b1", "parent": "r",
                     "t0_wall": 100.401, "dur_s": 0.098, "n": 2, "rid": 0},
                    {"name": "coalesce", "parent": "b1",
                     "t0_wall": 100.401, "dur_s": 0.002},
                    {"name": "rpc", "parent": "b1", "rid": 0,
                     "t0_wall": 100.403, "dur_s": 0.09},
                    {"name": "reply", "parent": "b1",
                     "t0_wall": 100.493, "dur_s": 0.001},
                ],
            )
    router.close()
    if with_traces:
        worker = EventBus(run_id="e" * 16, attempt=0, process_index=1)
        worker.bind_dir(tmp_path)
        worker.emit(
            "trace",
            trace_ids=[f"t{i}" for i in range(breaches)],
            span={"name": "device", "t0_wall": 100.41, "dur_s": 0.08,
                  "batch": "b1", "rid": 0, "n": 2},
        )
        worker.close()


def test_trace_report_merges_worker_spans_and_stars_widest(tmp_path):
    _emit_synthetic_run(tmp_path, with_traces=True)
    lines = []
    rc = run_report.trace_report(tmp_path, out=lines.append)
    assert rc == 0
    text = "\n".join(lines)
    assert "class gold" in text
    queue_line = next(l for l in lines if l.strip().startswith("queue"))
    assert "*widest" in queue_line  # 400ms queue dominates
    device_line = next(l for l in lines if l.strip().startswith("device"))
    assert "80" in device_line  # the worker span crossed the file join
    hop_line = next(l for l in lines if l.strip().startswith("hop"))
    assert "10" in hop_line  # rpc 90ms - device 80ms


def test_trace_report_exits_1_on_breaches_without_traces(tmp_path):
    _emit_synthetic_run(tmp_path, with_traces=False)
    lines = []
    rc = run_report.trace_report(tmp_path, out=lines.append)
    assert rc == 1
    assert any("NO TRACES FOR BREACHED CLASS" in l for l in lines)


def test_trace_report_skips_torn_record_keeps_survivors(tmp_path):
    _emit_synthetic_run(tmp_path, with_traces=True)
    # simulate a writer killed mid-record: a torn JSON tail on the
    # router's file (no newline), as after truncation/rotation
    f = tmp_path / "events.jsonl"
    with open(f, "ab") as fh:
        fh.write(b'{"v": 1, "kind": "trace", "payload": {"trace_id": "tor')
    lines = []
    rc = run_report.trace_report(tmp_path, out=lines.append)
    assert rc == 0
    assert any("kept traces: 2" in l for l in lines)


def test_event_tailer_buffers_torn_tail_until_completed(tmp_path):
    f = tmp_path / "events.jsonl"
    whole = json.dumps({"v": 1, "kind": "trace", "t_wall": 1.0})
    torn = json.dumps({"v": 1, "kind": "trace", "t_wall": 2.0})
    f.write_text(whole + "\n" + torn[:10])
    tailer = obs.EventTailer(tmp_path)
    first = tailer.poll()
    assert [e["t_wall"] for e in first] == [1.0]
    with open(f, "a") as fh:
        fh.write(torn[10:] + "\n")
    second = tailer.poll()
    assert [e["t_wall"] for e in second] == [2.0]


def test_diff_renders_dash_for_absent_segments(tmp_path):
    a_dir, b_dir = tmp_path / "a", tmp_path / "b"
    _emit_synthetic_run(a_dir, with_traces=True)
    _emit_synthetic_run(b_dir, with_traces=False)
    a, _ = run_report.load_run(a_dir)
    b, _ = run_report.load_run(b_dir)
    text = run_report.format_diff(
        "a", run_report.summarize(a), "b", run_report.summarize(b)
    )
    rows = {
        l.split("  ")[0].strip(): l for l in text.splitlines()
        if l.startswith("gold")
    }
    assert "gold queue p95 ms" in text
    queue_row = next(
        l for l in text.splitlines() if l.startswith("gold queue")
    )
    # run A measured ~400ms, run B kept nothing: number vs '-' — and the
    # delta of a missing side is '-' too, never a fabricated 0
    assert "400.0" in queue_row and "-" in queue_row
    assert rows  # the per-class rows exist at all


def test_trace_diff_cells_never_fabricate_zero(tmp_path):
    _emit_synthetic_run(tmp_path, with_traces=True)
    events, _ = run_report.load_run(tmp_path)
    cells = run_report.trace_diff_cells(events)
    assert cells["gold"]["n"] == 2
    assert abs(cells["gold"]["queue_p95_ms"] - 400.0) < 1.0
    assert abs(cells["gold"]["device_p95_ms"] - 80.0) < 1.0
    assert abs(cells["gold"]["transport_p95_ms"] - 10.0) < 1.0
    # a thread-transport run: no hop measured, cell absent not 0
    thread_dir = tmp_path / "thread"
    bus = EventBus(run_id="f" * 16)
    bus.bind_dir(thread_dir)
    bus.emit(
        "trace", trace_id="x", cls="gold", keep="sampled", sampled=True,
        outcome="completed", breach=False, requeues=0, deadline_ms=None,
        spans=[
            {"name": "request", "span_id": "r", "parent": None,
             "t0_wall": 1.0, "dur_s": 0.1},
            {"name": "device", "parent": "b", "rid": 0, "t0_wall": 1.0,
             "dur_s": 0.05},
        ],
    )
    bus.close()
    tevents, _ = run_report.load_run(thread_dir)
    tcells = run_report.trace_diff_cells(tevents)
    assert tcells["gold"]["transport_p95_ms"] is None
    assert tcells["gold"]["queue_p95_ms"] is None


# ------------------------------------ the REAL process fleet (slow e2e)


def _process_router(tmp_path, tracer):
    from test_serve_fleet import _bus
    from test_serve_process import _process_spec

    from distributed_training_comparison_tpu.serve import ServeRouter

    bus = _bus(tmp_path)
    spec = _process_spec(tmp_path)
    r = ServeRouter(
        None, replicas=1, transport="process", process_spec=spec,
        bus=bus, queue_limit=64, emit_every_s=0.5, tracer=tracer,
    )
    return bus, r


@pytest.mark.slow
@pytest.mark.serve_fleet
def test_process_fleet_sampled_traces_cross_the_wire(tmp_path):
    """Sample 1.0 on a real worker process: the device span is emitted
    eagerly from the worker's own bus (events-p1.jsonl) and the report
    merge reassembles the full tree, hop included, across files."""
    bus = EventBus(run_id="ab" * 8)
    bus.bind_dir(tmp_path)
    tracer = RequestTracer(bus=bus, sample_rate=1.0, seed=0)
    bus2, r = _process_router(tmp_path, tracer)
    try:
        assert r.wait_ready(n=1, timeout=600)
        img16 = np.zeros((16, 16, 3), np.uint8)
        for f in [r.submit(img16) for _ in range(4)]:
            f.result(timeout=120)
    finally:
        r.close()
    bus.close()
    assert (tmp_path / "events-p1.jsonl").exists()
    worker_traces = _trace_events(tmp_path, process_index=1)
    assert worker_traces, "worker never emitted a device span"
    events, _ = run_report.load_run(tmp_path)
    rows = run_report.trace_rows(events)
    assert len(rows) == 4
    for row in rows:
        assert row["keep"] == "sampled" and row["outcome"] == "completed"
        seg = row["segments"]
        # end-to-end reconstruction from event files alone
        for name in ("admit", "queue", "device", "reply"):
            assert seg.get(name) is not None, (name, seg)
        assert seg["device"] > 0 and seg["hop"] >= 0
    lines = []
    assert run_report.trace_report(tmp_path, out=lines.append) == 0
    assert any("*widest" in l for l in lines)


@pytest.mark.slow
@pytest.mark.serve_fleet
def test_process_fleet_breach_retro_flushes_device_span(tmp_path):
    """At sampling 0 a deadline-breached request is STILL fully
    reconstructable: the worker buffered its device span in the ring
    and the router's tail-keep decision flushed it over the next frame
    (or the drain), so the merge finds every segment after the fact."""
    bus = EventBus(run_id="cd" * 8)
    bus.bind_dir(tmp_path)
    tracer = RequestTracer(bus=bus, sample_rate=0.0, seed=0)
    bus2, r = _process_router(tmp_path, tracer)
    try:
        assert r.wait_ready(n=1, timeout=600)
        img16 = np.zeros((16, 16, 3), np.uint8)
        # probe the warm latency, then set a deadline half of it: the
        # first pop happens from an empty queue (so never queue-expired)
        # and completes late — a breach with a dispatched batch
        t0 = time.monotonic()
        r.submit(img16).result(timeout=120)
        probe_ms = (time.monotonic() - t0) * 1e3
        deadline_ms = max(2.0, probe_ms * 0.5)
        futs = [r.submit(img16, deadline_ms=deadline_ms)
                for _ in range(8)]
        for f in futs:
            try:
                f.result(timeout=120)
            except DeadlineExceeded:
                pass  # queue-expired stragglers are kept too
    finally:
        r.close()
    bus.close()
    events, _ = run_report.load_run(tmp_path)
    rows = run_report.trace_rows(events)
    reasons = {row["keep"] for row in rows}
    assert reasons <= {"deadline_breach", "expired"}
    breached = [r_ for r_ in rows if r_["keep"] == "deadline_breach"]
    assert breached, f"no breach kept (probe {probe_ms:.1f}ms): {reasons}"
    # the probe request itself was healthy at sample 0: not kept
    assert len(rows) <= 8
    dev = [r_ for r_ in breached
           if r_["segments"].get("device") is not None]
    assert dev, "retro-flush never delivered the worker device span"
    assert run_report.trace_report(tmp_path, out=lambda s: None) == 0


@pytest.mark.slow
@pytest.mark.serve_fleet
def test_process_fleet_kill_requeue_keeps_one_trace(tmp_path):
    """SIGKILL a worker mid-dispatch on a 2-replica fleet: the rescued
    request keeps ONE trace spanning both replicas — the failed attempt
    annotated ``requeued`` on the dead rid, the retry on the survivor."""
    import os
    import signal

    from test_serve_fleet import _bus, _wait
    from test_serve_process import _process_spec

    from distributed_training_comparison_tpu.serve import ServeRouter

    bus = _bus(tmp_path)
    spec = _process_spec(tmp_path, buckets=(1, 2), image_size=32)
    tracer = RequestTracer(bus=bus, sample_rate=0.0, seed=0)
    r = ServeRouter(
        None, replicas=2, transport="process", process_spec=spec,
        bus=bus, queue_limit=512, emit_every_s=0.5, tracer=tracer,
    )
    try:
        assert r.wait_ready(n=2, timeout=600)
        rep = r.replicas[0]
        pid = rep.pid
        img32 = np.zeros((32, 32, 3), np.uint8)
        futs = [r.submit(img32) for _ in range(64)]
        _wait(lambda: rep.dispatches >= 2, timeout=120,
              what="dispatches flowing")
        os.kill(pid, signal.SIGKILL)
        rows = [f.result(timeout=600) for f in futs]
        assert len(rows) == 64
    finally:
        r.close()
    events, _ = run_report.load_run(tmp_path)
    trows = run_report.trace_rows(events)
    requeued = [t for t in trows if t["keep"] == "requeued"]
    assert requeued, "kill-requeued request kept no trace"
    row = requeued[0]
    assert row["requeues"] >= 1 and row["outcome"] == "completed"
    # one trace_id, two replica attempts visible in its rid trail
    ev = next(
        e["payload"] for e in events
        if e.get("kind") == "trace"
        and e["payload"].get("trace_id") == row["trace_id"]
    )
    rpcs = [s for s in ev["spans"] if s["name"] == "rpc"]
    assert any(s.get("requeued") for s in rpcs), rpcs
    assert any(s.get("ok", True) and not s.get("requeued")
               for s in rpcs), rpcs


# ------------------------------------------------------- config + kinds


def test_serve_trace_sample_flag_parses_and_validates():
    from distributed_training_comparison_tpu.config import load_config

    hp = load_config("tpu", argv=["--serve-trace-sample", "0.25"])
    assert hp.serve_trace_sample == 0.25
    assert load_config("tpu", argv=[]).serve_trace_sample == 0.0
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--serve-trace-sample", "1.5"])
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--serve-trace-sample", "-0.1"])


def test_trace_kind_is_registered():
    assert "trace" in obs.KNOWN_KINDS
    ev = EventBus(run_id="9" * 16).emit(
        "trace", trace_id="t", cls="gold", keep="sampled", sampled=True,
        outcome="completed", breach=False, requeues=0, deadline_ms=None,
        spans=[],
    )
    assert not obs.validate_event(ev)
