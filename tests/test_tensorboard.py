"""Event-writer wire-format tests, validated against tensorboard's own
readers/protos (available in the image's TF stack, but NOT a runtime
dependency of the framework)."""

import glob

import pytest

from distributed_training_comparison_tpu.utils.tensorboard import (
    SummaryWriter,
    _event,
    _scalar_summary,
    crc32c,
)


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert crc32c(b"") == 0x0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA


def test_proto_bytes_match_real_protobuf():
    event_pb2 = pytest.importorskip("tensorboard.compat.proto.event_pb2")
    e = event_pb2.Event()
    e.wall_time = 123.5
    e.step = 7
    v = e.summary.value.add()
    v.tag = "loss/step"
    v.simple_value = 2.5
    mine = _event(123.5, 7, summary=_scalar_summary("loss/step", 2.5))
    assert mine == e.SerializeToString()


def test_event_file_roundtrip(tmp_path):
    loader_mod = pytest.importorskip(
        "tensorboard.backend.event_processing.event_file_loader"
    )
    event_pb2 = pytest.importorskip("tensorboard.compat.proto.event_pb2")
    with SummaryWriter(tmp_path) as w:
        w.add_scalar("acc/epoch", 71.17, 50)
        w.add_scalar("lr", 0.1, 0)
    f = glob.glob(str(tmp_path / "events.out.tfevents.*"))[0]
    events = []
    for raw in loader_mod.RawEventFileLoader(f).Load():
        e = event_pb2.Event()
        e.ParseFromString(raw)
        events.append(e)
    assert events[0].file_version == "brain.Event:2"
    scalars = {
        e.summary.value[0].tag: (e.step, e.summary.value[0].simple_value)
        for e in events[1:]
    }
    assert scalars["acc/epoch"][0] == 50
    assert scalars["acc/epoch"][1] == pytest.approx(71.17, abs=1e-4)
    assert scalars["lr"] == (0, pytest.approx(0.1))
