"""Live fleet operations (ISSUE 7): heartbeats + stall classification,
cross-host straggler attribution, resource telemetry, the OpenMetrics
exporter, the alert engine, and the satellites that ride along (per-
attempt clock-skew refit, xplane degrade-with-warning, report-tool
forward compatibility, and the event-kind registry lint).

The load-bearing properties pinned here:

- a lagging host is classified (slow vs dead) and reported as ONE
  ``stall`` transition per state change — never a flap stream;
- straggler attribution names host + phase from the per-process sketch
  streams alone, and a single-host run can never produce a finding;
- the exporter's exposition is strict OpenMetrics (a from-scratch parser
  validates TYPE lines, cumulative ``le`` series, the ``_total`` counter
  suffix, and the ``# EOF`` terminator) and its histogram buckets
  reconstruct the sketch's quantiles;
- alert rules honor their ``for=N`` hysteresis in BOTH directions and
  every emitted kind in the package is registered and documented.
"""

import json
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))

import goodput_report  # noqa: E402
import health_report  # noqa: E402
import run_report  # noqa: E402

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.obs.alerts import (
    AlertEngine,
    AlertRule,
    AlertSpecError,
)
from distributed_training_comparison_tpu.obs.bus import EventBus
from distributed_training_comparison_tpu.obs.heartbeat import (
    FleetWatcher,
    HeartbeatEmitter,
    LivenessTracker,
)
from distributed_training_comparison_tpu.obs.metrics import (
    Histogram,
    MetricRegistry,
)
from distributed_training_comparison_tpu.obs.resource import ResourceSampler
from distributed_training_comparison_tpu.obs.straggler import (
    host_phase_table,
    straggler_findings,
)

WORKER = Path(__file__).parent / "fleet_worker.py"


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    monkeypatch.delenv(obs.RUN_ID_ENV, raising=False)
    monkeypatch.delenv(obs.ATTEMPT_ENV, raising=False)
    obs.reset()
    yield
    obs.reset()


# -------------------------------------------------------------- heartbeats


def test_heartbeat_cadence_bounds_emission():
    bus = EventBus(run_id="ab" * 8)
    hb = HeartbeatEmitter(bus, every_s=3600.0)  # no second emit this test
    ev = hb.beat(epoch=0, step=1, flush_seq=0)
    assert ev is not None and ev["kind"] == "heartbeat"
    assert obs.validate_event(ev) == []
    assert ev["payload"]["flush_seq"] == 0 and ev["step"] == 1
    for i in range(50):
        assert hb.beat(epoch=0, step=2 + i) is None  # rate-limited
    assert hb.emitted == 1
    assert hb.beat(force=True) is not None  # epoch edges may force
    # ages() reflects the last CALL, not the last emit
    assert hb.ages()["p0"] < 1.0


def test_heartbeat_disabled_emits_nothing_but_tracks_age():
    bus = EventBus(run_id="ab" * 8)
    hb = HeartbeatEmitter(bus, every_s=0.0)
    assert hb.beat(epoch=0, step=1) is None
    assert hb.emitted == 0
    assert "p0" in hb.ages()


def _hb(process_index, t_wall, step=0, attempt=0):
    return {
        "v": 1, "run_id": "ab" * 8, "attempt": attempt,
        "process_index": process_index, "t_wall": t_wall, "t_mono": t_wall,
        "kind": "heartbeat", "epoch": 0, "step": step,
    }


def test_liveness_tracker_slow_dead_recovered_transitions():
    tr = LivenessTracker(heartbeat_s=1.0)  # slow > 3s, dead > 10s
    tr.observe(_hb(0, 0.0, step=100), now=0.0)
    tr.observe(_hb(1, 0.0, step=60), now=0.0)
    assert tr.check(now=1.0) == []  # everyone fresh
    tr.observe(_hb(0, 4.0, step=140), now=4.0)
    findings = tr.check(now=4.5)  # p1 is 4.5s stale -> slow; p0 fresh
    assert [f["process_index"] for f in findings] == [1]
    assert findings[0]["state"] == "slow"
    assert findings[0]["behind_steps"] == 140 - 60
    assert tr.check(now=5.0) == []  # still slow: no re-emission, no flap
    findings = tr.check(now=11.0)
    assert [(f["process_index"], f["state"]) for f in findings] == [
        (0, "slow"), (1, "dead"),
    ]
    tr.observe(_hb(1, 11.5, step=150), now=11.5)
    findings = tr.check(now=12.0)
    assert [(f["process_index"], f["state"]) for f in findings] == [
        (1, "recovered"),
    ]


def test_liveness_any_kind_refreshes_but_only_heartbeats_carry_position():
    tr = LivenessTracker(heartbeat_s=1.0)
    tr.observe(_hb(0, 0.0, step=10), now=0.0)
    ev = dict(_hb(0, 4.0, step=999), kind="epoch_end")
    tr.observe(ev, now=4.0)  # alive...
    assert tr.check(now=4.5) == []
    assert tr._procs[0]["step"] == 10  # ...but position is heartbeat-owned


def test_liveness_ignores_watcher_side_kinds():
    # the supervisor's own stall/alert/attempt events land in the tailed
    # root file as process-0 events; counting them as liveness would make
    # the tracker revive the very host it just called out (observed as a
    # slow→recovered flap loop on a real supervised run)
    tr = LivenessTracker(heartbeat_s=1.0)
    tr.observe(_hb(0, 0.0), now=0.0)
    for kind in ("stall", "straggler", "alert", "attempt_end", "backoff"):
        tr.observe(dict(_hb(0, 5.0), kind=kind), now=5.0)
    assert [f["state"] for f in tr.check(now=5.0)] == ["slow"]  # age is 5s


def test_liveness_no_dead_call_before_first_heartbeat():
    # run_start → first beat can be minutes of jit compile: silence before
    # a process has EVER beaten caps at "slow", never pages "dead"
    tr = LivenessTracker(heartbeat_s=1.0)
    tr.observe(dict(_hb(0, 0.0), kind="run_start"), now=0.0)
    findings = tr.check(now=100.0)
    assert [f["state"] for f in findings] == ["slow"]
    tr.observe(_hb(0, 101.0), now=101.0)  # first beat arrives
    assert [f["state"] for f in tr.check(now=102.0)] == ["recovered"]
    findings = tr.check(now=300.0)  # full silence AFTER a beat escalates
    assert [f["state"] for f in findings] == ["dead"]


def test_fleet_watcher_emits_stall_events_from_files(tmp_path):
    child = EventBus(run_id="ab" * 8, process_index=1)
    child.bind_dir(tmp_path / "version-0")
    child.emit("heartbeat", epoch=0, step=5)
    sup = EventBus(run_id="ab" * 8)
    sup.bind_dir(tmp_path)
    w = FleetWatcher(
        tmp_path, sup, tracker=LivenessTracker(heartbeat_s=1.0)
    )
    t0 = time.monotonic()
    w.step(now=t0)  # consumes the heartbeat; everyone fresh
    w.step(now=t0 + 11.0)  # p1 went silent past dead_after
    # the supervisor's own emits (the stall) also land in the tailed root,
    # but the tracker state machine emits once per transition only
    w.step(now=t0 + 12.0)
    stalls = [
        e for e in obs.load_events(tmp_path / "events.jsonl")
        if e["kind"] == "stall"
    ]
    # p1 raced straight past "slow" to "dead" between polls; the
    # supervisor's own p0 events keep IT alive
    assert [
        (e["payload"]["process_index"], e["payload"]["state"]) for e in stalls
    ] == [(1, "dead")]
    assert all(obs.validate_event(e) == [] for e in stalls)
    child.close()
    sup.close()


# -------------------------------------------------------------- stragglers


def _metrics_event(process_index, phase_values, attempt=0, step=50):
    reg_metrics = {}
    for phase, values in phase_values.items():
        hist = Histogram(f"step/{phase}_s")
        hist.record_many(values)
        reg_metrics[f"step/{phase}_s"] = hist.snapshot()
    return {
        "v": 1, "run_id": "ab" * 8, "attempt": attempt,
        "process_index": process_index, "t_wall": 1.0, "t_mono": 1.0,
        "kind": "metrics", "epoch": 0, "step": step,
        "payload": {"metrics": reg_metrics, "steps": 50},
    }


def test_straggler_attribution_names_host_and_phase():
    rng = np.random.default_rng(0)
    fast = lambda: rng.normal(0.10, 0.005, 40).clip(1e-4)  # noqa: E731
    events = [
        _metrics_event(0, {"dispatch": fast(), "compute": fast()}),
        _metrics_event(1, {"dispatch": fast() * 5, "compute": fast()}),
        _metrics_event(2, {"dispatch": fast(), "compute": fast()}),
    ]
    findings = straggler_findings(events)
    assert len(findings) == 1
    f = findings[0]
    assert (f["process_index"], f["phase"]) == (1, "dispatch")
    assert f["hosts"] == 3 and f["samples"] == 40
    assert f["p95_s"] > f["fleet_p95_s"]


def test_straggler_two_host_fleet_still_attributes():
    # leave-one-out baseline: with a symmetric median/MAD the pair would
    # score each other at exactly 1 MAD and nothing could ever flag
    rng = np.random.default_rng(1)
    fast = rng.normal(0.05, 0.002, 30).clip(1e-4)
    events = [
        _metrics_event(0, {"dispatch": fast}),
        _metrics_event(1, {"dispatch": fast * 8}),
    ]
    findings = straggler_findings(events)
    assert [(f["process_index"], f["phase"]) for f in findings] == [
        (1, "dispatch")
    ]


def test_straggler_balanced_fleet_and_single_host_produce_nothing():
    rng = np.random.default_rng(2)
    mk = lambda: rng.normal(0.1, 0.01, 30).clip(1e-4)  # noqa: E731
    balanced = [
        _metrics_event(p, {"dispatch": mk(), "h2d_wait": mk()})
        for p in range(4)
    ]
    assert straggler_findings(balanced) == []
    solo = [_metrics_event(0, {"dispatch": mk() * 100})]
    assert straggler_findings(solo) == []


def test_straggler_merges_across_flush_windows_per_host():
    # two flushes per host merge associatively before scoring
    rng = np.random.default_rng(3)
    fast = lambda: rng.normal(0.1, 0.005, 10).clip(1e-4)  # noqa: E731
    events = [
        _metrics_event(0, {"dispatch": fast()}, step=10),
        _metrics_event(0, {"dispatch": fast()}, step=20),
        _metrics_event(1, {"dispatch": fast() * 6}, step=10),
        _metrics_event(1, {"dispatch": fast() * 6}, step=20),
    ]
    table = host_phase_table(events)
    assert table[0][1]["dispatch"]["count"] == 20
    findings = straggler_findings(events)
    assert [(f["process_index"], f["samples"]) for f in findings] == [(1, 20)]


def test_straggler_events_and_report_table(tmp_path):
    rng = np.random.default_rng(4)
    fast = lambda: rng.normal(0.1, 0.005, 30).clip(1e-4)  # noqa: E731
    events = [
        _metrics_event(0, {"dispatch": fast()}),
        _metrics_event(1, {"dispatch": fast() * 7}),
    ]
    bus = EventBus(run_id="ab" * 8)
    bus.bind_dir(tmp_path)
    found = obs.emit_straggler_events(bus, events)
    assert len(found) == 1
    logged = [
        e for e in obs.load_events(tmp_path / "events.jsonl")
        if e["kind"] == "straggler"
    ]
    assert len(logged) == 1
    assert obs.validate_event(logged[0]) == []
    assert logged[0]["payload"]["process_index"] == 1
    # run_report's per-host table flags the same host+phase
    summary = run_report.summarize(events + logged)
    text = run_report.format_summary("r", summary)
    assert "per-host step phases" in text
    assert re.search(r"straggler: attempt 0 process 1 phase dispatch", text)
    bus.close()


# ---------------------------------------------------------------- resources


def test_resource_sampler_records_host_gauges(tmp_path):
    reg = MetricRegistry()
    sampler = ResourceSampler(ckpt_root=tmp_path)
    values = sampler.sample(reg)
    # linux CI: RSS, fds, and disk-free must all be present and sane
    assert values["res/host_rss_bytes"] > 1e6
    assert values["res/open_fds"] >= 3
    assert values["res/disk_free_bytes"] > 0
    snaps = reg.snapshot(reset=False)
    assert snaps["res/open_fds"]["type"] == "gauge"
    # the CPU CI backend reports no HBM stats — the gauge is absent, not 0
    # (on a TPU host the same call yields res/hbm_used_bytes)
    from distributed_training_comparison_tpu._compat import device_memory_stats
    import jax

    if device_memory_stats(jax.local_devices()[0]) is None:
        assert "res/hbm_used_bytes" not in values


def test_resource_sampler_no_ckpt_root_skips_disk():
    values = ResourceSampler().read()
    assert "res/disk_free_bytes" not in values
    assert "res/host_rss_bytes" in values


def test_resource_sampler_rate_limits_but_gauges_persist(tmp_path):
    reg = MetricRegistry()
    sampler = ResourceSampler(ckpt_root=tmp_path, min_interval_s=3600.0)
    assert sampler.sample(reg)  # first call always reads
    assert sampler.sample(reg) == {}  # within the interval: skipped
    assert sampler.samples == 1
    # the registry still carries the last sample on every later flush
    # (gauges are not reset by snapshot)
    assert reg.snapshot(reset=True)["res/open_fds"]["type"] == "gauge"
    assert "res/open_fds" in reg.snapshot(reset=False)


# ------------------------------------------------- OpenMetrics exposition


def parse_openmetrics(text: str) -> dict:
    """Strict-ish OpenMetrics parser: validates the exposition structure
    and returns {family: {"type": t, "samples": {name+labels: value}}}.
    Raises AssertionError on any violation."""
    assert text.endswith("# EOF\n"), "must terminate with # EOF"
    families: dict = {}
    current = None
    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(-?[0-9.eE+]+|[+-]Inf|NaN)$'
    )
    for line in text.splitlines()[:-1]:  # all but "# EOF"
        assert line.strip() == line and line, f"stray whitespace: {line!r}"
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ")
            assert mtype in ("counter", "gauge", "histogram"), line
            assert name not in families, f"duplicate family {name}"
            current = name
            families[name] = {"type": mtype, "samples": {}}
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = sample_re.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        assert current is not None, f"sample before any TYPE: {line!r}"
        mtype = families[current]["type"]
        if mtype == "counter":
            assert name == current + "_total", (
                f"counter sample must be {current}_total, got {name}"
            )
        elif mtype == "gauge":
            assert name == current, line
        else:
            assert name in (
                current + "_bucket", current + "_count", current + "_sum"
            ), f"histogram sample {name} outside family {current}"
            if name == current + "_bucket":
                assert 'le="' in labels, f"bucket without le: {line!r}"
        families[current]["samples"][name + labels] = float(value)
    # histogram invariants: cumulative non-decreasing buckets ending +Inf,
    # with _count equal to the +Inf bucket
    for fam, rec in families.items():
        if rec["type"] != "histogram":
            continue
        buckets = [
            (k, v) for k, v in rec["samples"].items()
            if k.startswith(fam + "_bucket")
        ]
        assert buckets and buckets[-1][0].endswith('le="+Inf"}'), (
            f"{fam}: last bucket must be +Inf"
        )
        counts = [v for _k, v in buckets]
        assert counts == sorted(counts), f"{fam}: buckets must be cumulative"
        assert rec["samples"][fam + "_count"] == counts[-1]
    return families


def test_render_openmetrics_strict_and_quantile_roundtrip():
    reg = MetricRegistry(flush_steps=4)
    rng = np.random.default_rng(0)
    samples = rng.lognormal(0.0, 1.0, 4000)
    reg.histogram("train/loss").record_many(samples)
    reg.counter("train/skipped_steps").inc(3)
    reg.gauge("res/open_fds").set(41)
    bus = EventBus(run_id="ab" * 8)
    reg.note_steps(4)
    reg.flush(bus, epoch=0)
    reg.histogram("train/loss").record_many(samples)  # pending window

    fams = parse_openmetrics(
        obs.render_openmetrics(
            reg.cumulative_snapshot(), {"p0": 0.5}, {"rule:p99>1": False}
        )
    )
    assert fams["dtc_train_skipped_steps"]["samples"][
        "dtc_train_skipped_steps_total"
    ] == 3
    assert fams["dtc_res_open_fds"]["samples"]["dtc_res_open_fds"] == 41
    hist = fams["dtc_train_loss"]["samples"]
    assert hist["dtc_train_loss_count"] == 2 * len(samples)  # cumulative
    assert fams["dtc_heartbeat_age_seconds"]["samples"][
        'dtc_heartbeat_age_seconds{process="0"}'
    ] == 0.5
    assert fams["dtc_alert_firing"]["samples"][
        'dtc_alert_firing{spec="rule:p99>1"}'
    ] == 0
    # p95 reconstructed from the RENDERED buckets matches the exact one
    # within the sketch's bucket-ratio error
    les, counts = [], []
    for key, v in hist.items():
        m = re.search(r'le="([^"]+)"', key)
        if m and m.group(1) != "+Inf":
            les.append(float(m.group(1)))
            counts.append(v)
    order = np.argsort(les)
    les, counts = np.asarray(les)[order], np.asarray(counts)[order]
    rank = 0.95 * hist["dtc_train_loss_count"]
    p95_rendered = les[np.searchsorted(counts, rank)]
    assert abs(p95_rendered - np.quantile(samples, 0.95)) / p95_rendered < 0.2


def test_render_openmetrics_zeros_count_into_every_bucket():
    h = Histogram("x")
    h.record_many([0.0, 0.0, 5.0])
    fams = parse_openmetrics(
        obs.render_openmetrics({"x": h.snapshot()})
    )
    samples = fams["dtc_x"]["samples"]
    first_bucket = min(
        (k for k in samples if "_bucket{" in k and "+Inf" not in k),
        key=lambda k: float(re.search(r'le="([^"]+)"', k).group(1)),
    )
    assert samples[first_bucket] == 3  # the two zeros sit below every le
    assert samples["dtc_x_count"] == 3


def test_exporter_http_scrape_and_404():
    reg = MetricRegistry()
    reg.gauge("res/open_fds").set(7)
    hb = HeartbeatEmitter(EventBus(run_id="ab" * 8), every_s=60)
    hb.beat()
    exp = obs.MetricsExporter(port=0, registry=reg, heartbeats=hb).start()
    try:
        url = f"http://127.0.0.1:{exp.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as r:
            assert r.headers["Content-Type"].startswith(
                "application/openmetrics-text"
            )
            body = r.read().decode()
        fams = parse_openmetrics(body)
        assert fams["dtc_res_open_fds"]["samples"]["dtc_res_open_fds"] == 7
        assert "dtc_heartbeat_age_seconds" in fams
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/nope", timeout=5
            )
        assert exp.scrapes == 1
    finally:
        exp.close()
    # closed: the port no longer accepts
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", exp.port), timeout=0.5)


def test_start_exporter_flag_semantics():
    assert obs.start_exporter(0) is None  # 0 = off
    reg = MetricRegistry()
    exp = obs.start_exporter(_free_port(), process_index=0, registry=reg)
    try:
        assert exp is not None
        # a second process on the same base port gets port+1
        exp2 = obs.start_exporter(exp.port, process_index=1, registry=reg)
        try:
            assert exp2 is not None and exp2.port == exp.port + 1
        finally:
            if exp2 is not None:
                exp2.close()
        # a taken port returns None instead of raising
        assert obs.start_exporter(exp.port, process_index=0) is None
    finally:
        exp.close()


def test_start_exporter_port_overflow_degrades_to_none():
    # a valid base port on a wide host: 65535 + process_index overflows
    # bind()'s range — must degrade like a taken port, not kill training
    assert obs.start_exporter(65535, process_index=7) is None


def test_cumulative_snapshot_is_monotone_across_concurrent_flushes():
    # a scrape racing flush's reset-then-fold must never see a counter dip
    reg = MetricRegistry(flush_steps=1)
    bus = EventBus(run_id="ab" * 8)
    stop = threading.Event()
    dips = []

    def scraper():
        last = 0
        while not stop.is_set():
            snap = reg.cumulative_snapshot().get("c")
            n = (snap or {}).get("n", 0)
            if n < last:
                dips.append((last, n))
            last = n

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    for i in range(300):
        reg.counter("c").inc(1)
        reg.note_steps(1)
        reg.flush(bus, step=i)
    stop.set()
    t.join(timeout=10)
    assert not dips, dips
    assert reg.cumulative_snapshot()["c"]["n"] == 300


def test_alert_ticker_fires_age_rule_without_manual_ticks():
    bus = EventBus(run_id="ab" * 8)
    hb = HeartbeatEmitter(bus, every_s=60)
    hb.beat()
    eng = AlertEngine(
        [AlertRule.parse("heartbeat:age>0.1:for=1")],
        bus=bus, heartbeats=hb,
    )
    eng.start_ticker(interval_s=0.05)
    try:
        deadline = time.monotonic() + 10.0
        while not eng.firing and time.monotonic() < deadline:
            time.sleep(0.05)  # the monitored thread "hangs" (never ticks)
        assert eng.firing
    finally:
        eng.close()


def test_export_openmetrics_any_firing_source_wins(tmp_path):
    # p0 fired and resolved LAST in the stream; p1 is still firing — the
    # exported state must be firing (per-source OR, not last-writer-wins)
    _write_events(
        tmp_path / "events.jsonl",
        [
            _alert_ev("firing", source="p1", t=1.0),
            _alert_ev("firing", source="p0", t=2.0),
            _alert_ev("resolved", source="p0", t=3.0),
        ],
    )
    fams = parse_openmetrics(run_report.export_openmetrics(tmp_path))
    assert fams["dtc_alert_firing"]["samples"][
        'dtc_alert_firing{spec="x:p99>1:for=1"}'
    ] == 1


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# -------------------------------------------------------------------- alerts


def test_alert_spec_parse_good_and_bad():
    r = AlertRule.parse("serve/latency_s:p99>0.25:for=3")
    assert (r.metric, r.agg, r.cmp, r.threshold, r.for_windows) == (
        "serve/latency_s", "p99", ">", 0.25, 3
    )
    r2 = AlertRule.parse("res/disk_free_bytes:value<1e9")
    assert r2.for_windows == 1 and r2.cmp == "<" and r2.threshold == 1e9
    assert AlertRule.parse("heartbeat:age>30").on_heartbeat
    for bad in (
        "nonsense", "m:p99", "m:p99>x", "m:bogus>1", "heartbeat:p99>1",
        "train/loss:age>1", "m:p99>1:for=z",
    ):
        with pytest.raises(AlertSpecError):
            AlertRule.parse(bad)
    # and the CLI rejects them before any training starts
    with pytest.raises(SystemExit):
        load_config("tpu", ["--synthetic-data", "--alert", "m:bogus>1"])


def _flush_ev(metric, snap, process_index=0, step=0):
    return {
        "v": 1, "run_id": "ab" * 8, "attempt": 0,
        "process_index": process_index, "t_wall": 1.0, "t_mono": 1.0,
        "kind": "metrics", "step": step,
        "payload": {"metrics": {metric: snap}},
    }


def _gauge(v):
    return {"type": "gauge", "value": v}


def test_alert_engine_for_hysteresis_both_directions(tmp_path):
    bus = EventBus(run_id="ab" * 8)
    bus.bind_dir(tmp_path)
    eng = AlertEngine([AlertRule.parse("res/open_fds:value>100:for=3")], bus=bus)
    for i, v in enumerate((150, 160, 120)):  # 3 consecutive breaches
        eng.observe_event(_flush_ev("res/open_fds", _gauge(v), step=i))
        assert eng.firing == (i == 2)  # fires exactly on the 3rd
    eng.observe_event(_flush_ev("res/open_fds", _gauge(50), step=3))
    assert eng.firing  # one clean window is NOT a resolve yet
    eng.observe_event(_flush_ev("res/open_fds", _gauge(200), step=4))
    eng.observe_event(_flush_ev("res/open_fds", _gauge(40), step=5))
    eng.observe_event(_flush_ev("res/open_fds", _gauge(40), step=6))
    assert eng.firing  # breach reset the clean count
    eng.observe_event(_flush_ev("res/open_fds", _gauge(40), step=7))
    assert not eng.firing  # 3 consecutive clean windows resolve
    events = obs.load_events(tmp_path / "events.jsonl")
    states = [e["payload"]["state"] for e in events if e["kind"] == "alert"]
    assert states == ["firing", "resolved"]
    assert all(
        obs.validate_event(e) == [] for e in events if e["kind"] == "alert"
    )
    bus.close()


def test_alert_engine_histogram_quantile_and_per_process_sources():
    h_fast, h_slow = Histogram("l"), Histogram("l")
    h_fast.record_many(np.full(100, 0.01))
    h_slow.record_many(np.full(100, 0.9))
    eng = AlertEngine([AlertRule.parse("serve/latency_s:p99>0.25:for=1")])
    eng.observe_event(
        _flush_ev("serve/latency_s", h_fast.snapshot(), process_index=0)
    )
    eng.observe_event(
        _flush_ev("serve/latency_s", h_slow.snapshot(), process_index=1)
    )
    assert eng.firing
    # host 1 breached; host 0's clean window did not average it away
    assert [t["source"] for t in eng.transitions] == ["p1"]


def test_alert_engine_serve_record_latency_delta_counts():
    h = Histogram("l")
    h.record_many(np.full(50, 0.5))
    eng = AlertEngine([AlertRule.parse("serve/latency_s:p95>0.25:for=1")])
    eng.observe_event({
        "v": 1, "run_id": "ab" * 8, "attempt": 0, "process_index": 0,
        "t_wall": 1.0, "t_mono": 1.0, "kind": "serve",
        "payload": {"completed": 50, "latency_hist": h.snapshot()},
    })
    assert eng.firing


def test_alert_engine_heartbeat_age_rule_via_tick():
    tr = LivenessTracker(heartbeat_s=1.0)
    tr.observe(_hb(0, 0.0), now=0.0)
    tr.observe(_hb(1, 0.0), now=0.0)
    eng = AlertEngine(
        [AlertRule.parse("heartbeat:age>30:for=1")], heartbeats=tr
    )
    eng.tick(now=10.0)
    assert not eng.firing
    tr.observe(_hb(0, 35.0), now=35.0)  # p0 alive, p1 silent
    eng.tick(now=36.0)
    assert eng.states() == {"heartbeat:age>30:for=1": True}
    assert [t["source"] for t in eng.transitions] == ["p1"]
    tr.observe(_hb(1, 37.0), now=37.0)
    eng.tick(now=38.0)
    assert not eng.firing
    assert [t["state"] for t in eng.transitions] == ["firing", "resolved"]


def test_bus_subscription_feeds_engine_without_recursion(tmp_path):
    bus = EventBus(run_id="ab" * 8)
    bus.bind_dir(tmp_path)
    eng = AlertEngine([AlertRule.parse("res/open_fds:value>10:for=1")], bus=bus)
    bus.subscribe(eng.observe_event)
    reg = MetricRegistry(flush_steps=1)
    reg.gauge("res/open_fds").set(99)
    reg.note_steps(1)
    reg.flush(bus, epoch=0)  # emit -> tap -> engine -> alert emit (no loop)
    kinds = [e["kind"] for e in obs.load_events(tmp_path / "events.jsonl")]
    assert kinds == ["metrics", "alert"]
    bus.close()


# -------------------------------------------- run_report --alerts / export


def _write_events(path, events):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(e) + "\n" for e in events))


def _alert_ev(state, spec="x:p99>1:for=1", source="p1", t=1.0):
    return {
        "v": 1, "run_id": "ab" * 8, "attempt": 0, "process_index": 0,
        "t_wall": t, "t_mono": t, "kind": "alert",
        "payload": {
            "spec": spec, "metric": spec.split(":")[0], "state": state,
            "value": 2.0, "threshold": 1.0, "source": source,
        },
    }


def test_run_report_alerts_exit_codes(tmp_path, capsys):
    fired = tmp_path / "fired"
    _write_events(
        fired / "events.jsonl",
        [_alert_ev("firing", t=1.0)],
    )
    assert run_report.main([str(fired), "--alerts"]) == 1
    out = capsys.readouterr().out
    assert "FIRING" in out and "x:p99>1" in out

    resolved = tmp_path / "resolved"
    _write_events(
        resolved / "events.jsonl",
        [_alert_ev("firing", t=1.0), _alert_ev("resolved", t=2.0)],
    )
    assert run_report.main([str(resolved), "--alerts"]) == 0

    quiet = tmp_path / "quiet"
    _write_events(quiet / "events.jsonl", [_hb(0, 1.0)])
    assert run_report.main([str(quiet), "--alerts"]) == 0


def test_run_report_export_openmetrics_offline(tmp_path, capsys):
    h = Histogram("train/loss")
    h.record_many([1.0, 2.0, 4.0])
    _write_events(
        tmp_path / "version-0" / "events.jsonl",
        [
            _flush_ev("train/loss", h.snapshot(), step=10),
            _hb(0, t_wall=5.0),
            _alert_ev("firing", t=6.0),
        ],
    )
    out_file = tmp_path / "metrics.om"
    run_report.main(
        [str(tmp_path), "--export-openmetrics", str(out_file)]
    )
    fams = parse_openmetrics(out_file.read_text())
    assert fams["dtc_train_loss"]["samples"]["dtc_train_loss_count"] == 3
    assert "dtc_heartbeat_age_seconds" in fams
    assert fams["dtc_alert_firing"]["samples"][
        'dtc_alert_firing{spec="x:p99>1:for=1"}'
    ] == 1


# ------------------------------------------------ satellites: clock skew


def _anchor(process_index, attempt, t_wall):
    return {
        "v": 1, "run_id": "ab" * 8, "attempt": attempt,
        "process_index": process_index, "t_wall": t_wall, "t_mono": t_wall,
        "kind": "run_start",
    }


def test_skew_refit_per_attempt_tracks_drift():
    # attempt 0: host 1 is +5s; attempt 1 (a day of drift later): +9s —
    # one constant per host would mis-place one attempt by 4s
    events = []
    for attempt, skew in ((0, 5.0), (1, 9.0)):
        t = 100.0 * (attempt + 1)
        events += [
            _anchor(0, attempt, t),
            _anchor(1, attempt, t + skew),
            dict(_hb(0, t + 10.0, attempt=attempt), kind="epoch_end"),
            dict(_hb(1, t + 10.0 + skew, attempt=attempt), kind="epoch_end"),
        ]
    offsets = run_report.estimate_clock_skew_by_attempt(events)
    assert offsets[(1, 0)] == pytest.approx(5.0)
    assert offsets[(1, 1)] == pytest.approx(9.0)
    assert offsets[(1, None)] == pytest.approx(7.0)  # the fallback median
    shifted = run_report.apply_clock_skew(events, offsets)
    for ev in shifted:
        if ev["process_index"] == 1:
            base = 100.0 * (ev["attempt"] + 1)
            expect = base if ev["kind"] == "run_start" else base + 10.0
            assert ev["t_wall"] == pytest.approx(expect)
    # an attempt that died pre-anchor falls back to the across-attempt fit
    orphan = dict(_hb(1, 310.0, attempt=2), kind="epoch_end")
    [shifted_orphan] = run_report.apply_clock_skew([orphan], offsets)
    assert shifted_orphan["t_wall"] == pytest.approx(310.0 - 7.0)
    # the legacy per-process shape still applies (older callers/tests)
    legacy = run_report.estimate_clock_skew(events)
    assert legacy[1] == pytest.approx(7.0)
    assert run_report.apply_clock_skew([orphan], legacy)[0][
        "t_wall"
    ] == pytest.approx(310.0 - 7.0)


# ------------------------------------------------- satellites: xplane


def test_xplane_unknown_planes_and_no_step_ids_degrade_with_warning(tmp_path):
    # reuse test_telemetry's wire-format builders
    from test_telemetry import _pb_field, _pb_msg, _pb_varint  # noqa: E402

    # a plane with a RENAMED device plane name, no StepTraceAnnotations
    # (one plain "SomeOp" event); followed by a garbage sibling plane
    ev_meta = _pb_field(4, 2, _pb_msg(        # event_metadata map entry
        _pb_field(1, 0, _pb_varint(1)),
        _pb_field(2, 2, _pb_msg(
            _pb_field(1, 0, _pb_varint(1)),
            _pb_field(2, 2, b"SomeOp"),
        )),
    ))
    line = _pb_field(3, 2, _pb_msg(           # XPlane.lines
        _pb_field(2, 2, b"renamed-device-lane"),
        _pb_field(3, 0, _pb_varint(1000)),    # timestamp_ns
        _pb_field(4, 2, _pb_msg(              # XLine.events: no stats
            _pb_field(1, 0, _pb_varint(1)),
            _pb_field(2, 0, _pb_varint(0)),
            _pb_field(3, 0, _pb_varint(5_000_000)),
        )),
    ))
    plane = _pb_field(1, 2, _pb_msg(          # XSpace.planes
        _pb_field(2, 2, b"/device:FUTURE_XPU:0"),
        ev_meta, line,
    ))
    # siblings that must be skipped with warnings, not crash the parse:
    # wire garbage, and a decodable plane whose name field is a varint
    # (an int has no .decode — the AttributeError containment path)
    int_name_plane = _pb_field(1, 2, _pb_msg(_pb_field(2, 0, _pb_varint(5))))
    doc = plane + int_name_plane + _pb_field(1, 2, b"\xff\xff\xff\xff")
    prof = tmp_path / "prof"
    prof.mkdir()
    (prof / "host.xplane.pb").write_bytes(doc)
    host_dir = tmp_path / "run"
    host_dir.mkdir()
    (host_dir / "trace.json").write_text(json.dumps({
        "traceEvents": [
            {"ph": "X", "name": "dispatch", "pid": 0, "tid": 0,
             "ts": 50.0, "dur": 10.0, "args": {"step": 3}},
        ]
    }))
    out = tmp_path / "merged.json"
    logs: list[str] = []
    rc = run_report.xplane_merge(host_dir, prof, out, log=logs.append)
    assert rc == 0
    merged = json.loads(out.read_text())
    names = [e.get("name") for e in merged["traceEvents"]]
    assert "SomeOp" in names and "dispatch" in names  # both lanes survived
    lanes = [
        e["args"]["name"] for e in merged["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    ]
    assert "renamed-device-lane" in lanes  # unknown plane names pass through
    joined = " ".join(logs)
    assert "undecodable plane" in joined or "decode stopped early" in joined
    assert "aligned on first-event time" in joined  # degraded, loudly


# ------------------------- satellites: report-tool forward compatibility


def test_goodput_report_skips_future_kinds(tmp_path):
    events = [
        _hb(0, 1.0),
        _alert_ev("firing"),
        {
            "v": 1, "run_id": "ab" * 8, "attempt": 0, "process_index": 0,
            "t_wall": 2.0, "t_mono": 2.0, "kind": "goodput",
            "payload": {"step_s": 6.0, "wall_s": 10.0},
        },
        dict(_hb(0, 3.0), kind="kind_from_the_future"),
    ]
    path = tmp_path / "events.jsonl"
    _write_events(path, events)
    rep = goodput_report.load_report(path)
    assert rep["attempts"] == 1  # exactly the one goodput record
    assert rep["productive_s"] == pytest.approx(6.0)


def test_health_report_skips_future_kinds(tmp_path, capsys):
    events = [
        dict(_hb(0, 1.0), kind="skip", payload={"count": 2}),
        _hb(0, 2.0),
        _alert_ev("firing"),
        dict(_hb(0, 3.0), kind="kind_from_the_future", payload={"x": 1}),
        dict(_hb(0, 4.0), kind="rollback", payload={"wasted_steps": 9}),
    ]
    path = tmp_path / "health.jsonl"
    _write_events(path, events)
    rep = health_report.load_report(path)
    assert rep["skipped_steps"] == 2 and rep["rollbacks"] == 1
    assert health_report.main([str(path)]) == 0
    out = capsys.readouterr().out
    # unknown kinds are condensed, not echoed and never fatal
    assert "kind_from_the_future×1" in out
    assert "heartbeat×1" in out


def test_run_report_summarize_tolerates_future_kind():
    events = [
        _hb(0, 1.0),
        dict(_hb(0, 2.0), kind="run_start"),
        dict(_hb(0, 3.0), kind="kind_from_the_future", payload={"x": 1}),
    ]
    s = run_report.summarize(events)
    assert s["attempts"][0]["heartbeats"] == 1
    assert "kind_from_the_future" in run_report.format_timeline(events)


# ------------------------------------ satellite: event-kind registry lint


def test_every_emitted_kind_is_registered_and_documented():
    pkg_root = Path(obs.__file__).resolve().parent.parent
    emit_re = re.compile(
        r"""(?:\bemit|\b_events?)\(\s*\n?\s*["']([a-z_]+)["']"""
    )
    const_re = re.compile(r"""^[A-Z_]*KIND\s*=\s*["']([a-z_]+)["']""", re.M)
    emitted: set[str] = set()
    for py in sorted(pkg_root.rglob("*.py")):
        src = py.read_text()
        emitted |= set(emit_re.findall(src))
        emitted |= set(const_re.findall(src))
    # sanity: the scan actually sees the emitters (old, new, and constants)
    for expected in ("run_start", "heartbeat", "stall", "skip", "metrics",
                     "attempt_start", "serve", "alert", "straggler"):
        assert expected in emitted, f"scan lost {expected}"
    unregistered = emitted - obs.KNOWN_KINDS
    assert not unregistered, (
        f"kinds emitted but not in obs.bus.KNOWN_KINDS: {unregistered} — "
        "register them (and document them in the README kind table)"
    )
    readme = (pkg_root.parent / "README.md").read_text()
    kind_row = next(
        line for line in readme.splitlines()
        if line.startswith("| `kind` |")
    )
    undocumented = {
        k for k in obs.KNOWN_KINDS if f"`{k}`" not in kind_row
        # epoch_start/end share one `epoch_start/end` cell, attempt_* too
        and not (
            k in ("epoch_start", "epoch_end") and "`epoch_start/end`" in kind_row
        )
        and not (
            k in ("attempt_start", "attempt_end")
            and "`attempt_start/end`" in kind_row
        )
    }
    assert not undocumented, (
        f"kinds registered but missing from the README kind table: "
        f"{undocumented}"
    )


# ---------------------------------------------------- trainer + e2e legs


def test_trainer_heartbeats_resources_and_exporter(tmp_path):
    """In-process acceptance leg: a real training run emits heartbeats,
    samples the resource gauges into its flushes, and serves OpenMetrics
    on --metrics-port, scraped over HTTP while the trainer is live."""
    from test_train import TinyNet  # noqa: E402

    from distributed_training_comparison_tpu.train import Trainer

    port = _free_port()
    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "640",
            "--batch-size", "32", "--epoch", "2",
            "--save-last-min-secs", "0", "--no-progress",
            "--seed", "7", "--eval-step", "1000",
            "--ckpt-path", str(tmp_path),
            "--metrics-flush-steps", "8",
            "--heartbeat-secs", "0.01",
            "--metrics-port", str(port),
            "--device-chunk-steps", "6",
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    scrape: dict = {}

    def scraper():
        # retry until the exposition carries liveness (the first beat
        # lands only after the first chunk dispatch compiles)
        url = f"http://127.0.0.1:{trainer.exporter.port}/metrics"
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2) as r:
                    body = r.read().decode()
                if "dtc_heartbeat_age_seconds" in body:
                    scrape["body"] = body
                    return
            except OSError:
                pass
            time.sleep(0.1)

    try:
        assert trainer.exporter is not None and trainer.exporter.port == port
        t = threading.Thread(target=scraper, daemon=True)
        t.start()
        trainer.fit()
        t.join(timeout=60)
        # the live endpoint served a strict exposition during/after fit
        fams = parse_openmetrics(scrape["body"])
        assert "dtc_heartbeat_age_seconds" in fams
        # the post-fit registry view carries everything cumulative
        final = parse_openmetrics(trainer.exporter.render())
        assert final["dtc_train_loss"]["samples"]["dtc_train_loss_count"] == 36
        assert "dtc_res_host_rss_bytes" in final
        assert trainer.heartbeat.emitted >= 2
    finally:
        trainer.close()
    # exporter is down with the trainer
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", port), timeout=0.5)

    events = obs.load_events(tmp_path / "version-0" / "events.jsonl")
    assert all(obs.validate_event(e) == [] for e in events)
    beats = [e for e in events if e["kind"] == "heartbeat"]
    assert beats and all("flush_seq" in e["payload"] for e in beats)
    flushes = [e for e in events if e["kind"] == "metrics"]
    merged = {
        name
        for e in flushes
        for name in (e["payload"].get("metrics") or {})
    }
    assert "res/host_rss_bytes" in merged and "res/open_fds" in merged
    assert "res/disk_free_bytes" in merged


@pytest.mark.obs
def test_e2e_supervised_fleet_with_injected_slow_host(tmp_path):
    """ISSUE 7 acceptance: a supervised run whose attempt carries an
    injected per-host slowdown (fleet_worker emulates host 1 at the
    file level: slowed dispatch sketches, then a dead-then-recovered
    silence) produces straggler attribution naming host 1 + dispatch, a
    stall call for host 1, a firing→resolved heartbeat-age alert pair on
    the merged timeline, a still-firing dispatch alert that makes
    ``run_report --alerts`` exit nonzero, and a timeline that passes
    ``--check``."""
    root = tmp_path / "run"
    cmd = [
        sys.executable, str(WORKER), "--supervise",
        "--synthetic-data", "--limit-examples", "640",
        "--batch-size", "32", "--epoch", "2",
        "--no-progress", "--eval-step", "1000",
        "--save-last-min-secs", "0", "--seed", "7",
        "--ckpt-path", str(root),
        "--metrics-flush-steps", "6",
        "--device-chunk-steps", "3",
        "--heartbeat-secs", "0.2",
        "--goodput-json", str(tmp_path / "GOODPUT.json"),
        "--alert", "step/dispatch_s:p95>0.2:for=1",
        "--alert", "heartbeat:age>2:for=1",
    ]
    proc = subprocess.run(
        cmd, cwd=WORKER.parent.parent, capture_output=True, text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "Traceback" not in (proc.stderr or ""), proc.stderr[-3000:]

    events, _files = run_report.load_run(root)
    kinds = {e["kind"] for e in events}
    assert {"heartbeat", "metrics", "stall", "straggler", "alert"} <= kinds

    # stall: the emulated host 1 was called slow and/or dead, then recovered
    stalls = [
        e["payload"] for e in events
        if e["kind"] == "stall" and e["payload"].get("process_index") == 1
    ]
    assert any(s["state"] in ("slow", "dead") for s in stalls), stalls
    assert any(s["state"] == "recovered" for s in stalls), stalls

    # straggler attribution names the right host AND phase
    stragglers = [e["payload"] for e in events if e["kind"] == "straggler"]
    assert [(s["process_index"], s["phase"]) for s in stragglers] == [
        (1, "dispatch")
    ], stragglers

    # the heartbeat-age alert fired during the silence and resolved on the
    # recovery beat — a firing/resolved pair for source p1 on the timeline
    hb_alerts = [
        e["payload"] for e in events
        if e["kind"] == "alert" and e["payload"]["metric"] == "heartbeat"
        and e["payload"].get("source") == "p1"
    ]
    assert [a["state"] for a in hb_alerts] == ["firing", "resolved"], hb_alerts
    # the dispatch-latency alert fired on host 1's slowed sketch and never
    # saw a clean window — still firing, so --alerts gates nonzero
    disp_alerts = [
        e["payload"] for e in events
        if e["kind"] == "alert" and e["payload"]["metric"] == "step/dispatch_s"
    ]
    assert disp_alerts and disp_alerts[-1]["state"] == "firing"
    assert run_report.main([str(root), "--alerts"]) == 1

    # the merged stream stays schema-clean and the summary renders the
    # per-host table with host 1 flagged
    assert run_report.main([str(root), "--check"]) == 0
    text = run_report.format_summary("e2e", run_report.summarize(events))
    assert "straggler: attempt 0 process 1 phase dispatch" in text
    assert "heartbeats:" in text
