"""tools/convergence_parity.py — the 50-epoch torch-vs-flax harness.

The full run is an offline evidence artifact (hours of single-core torch);
CI pins the pieces that make the comparison valid: the torch-side
normalize/augment must be the same transform the flax path applies, and
the torch-side loop must run end to end on tiny settings.
"""

import importlib.util
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

spec = importlib.util.spec_from_file_location(
    "convergence_parity", REPO / "tools" / "convergence_parity.py"
)
cp = importlib.util.module_from_spec(spec)
spec.loader.exec_module(cp)

from distributed_training_comparison_tpu.data.augment import (  # noqa: E402
    normalize_images,
)


def test_normalize_matches_flax_pipeline():
    """The torch side's numpy normalize must be bit-comparable to the flax
    path's normalize_images (same mean/std, ToTensor semantics) — else the
    two frameworks would train on different data."""
    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 256, (4, 32, 32, 3), dtype=np.uint8)
    ours = cp._normalize_np(u8)  # NCHW fp32
    flax = np.transpose(np.asarray(normalize_images(jnp.asarray(u8))), (0, 3, 1, 2))
    np.testing.assert_allclose(ours, flax, atol=1e-6)


def test_augment_np_is_pad4_crop_flip():
    """Every augmented image must be a 32×32 window of the zero-padded
    input, possibly h-flipped — the reference's train transform."""
    rng = np.random.default_rng(1)
    u8 = rng.integers(1, 256, (6, 32, 32, 3), dtype=np.uint8)  # min 1: pad is 0
    out = cp._augment_np(u8, np.random.default_rng(42))
    assert out.shape == u8.shape and out.dtype == u8.dtype
    pad = 4
    padded = np.pad(u8, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    for i in range(len(u8)):
        found = False
        for r in range(2 * pad + 1):
            for c in range(2 * pad + 1):
                win = padded[i, r : r + 32, c : c + 32]
                if (out[i] == win).all() or (out[i] == win[:, ::-1]).all():
                    found = True
                    break
            if found:
                break
        assert found, f"image {i} is not a crop/flip of its padded source"
    # determinism in the seed
    out2 = cp._augment_np(u8, np.random.default_rng(42))
    np.testing.assert_array_equal(out, out2)


@pytest.mark.slow
def test_torch_side_smoke():
    """One tiny torch-side epoch end to end (reference net + recipe on the
    loader's splits); finite metrics with the expected keys."""
    result = cp.main(
        [
            "--skip-flax", "--epochs", "1", "--limit-examples", "256",
            "--batch-size", "64", "--noise", "0.45",
        ]
    )
    t = result["torch"]
    for k in ("test_loss", "test_top1", "test_top5", "best_val_acc"):
        assert np.isfinite(t[k]), k
