"""ViT family: architecture invariants + the BN-free end-to-end path.

The reference zoo is CNN-only; the transformer family is beyond-parity, so
there is no reference param-count to mirror — instead the count is checked
against the closed-form architecture formula, and the trainer path is
exercised end-to-end (a BN-free model must flow through the same scanned
epoch/eval programs that carry ResNet's batch_stats)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_comparison_tpu import models
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.models import ViT
from distributed_training_comparison_tpu.train import Trainer


def _param_count(depth, dim, heads, patch, num_classes, tokens, mlp_ratio=4):
    patch_embed = patch * patch * 3 * dim + dim
    pos = tokens * dim
    per_block = (
        2 * 2 * dim  # two LayerNorms (scale+bias)
        + 3 * (dim * dim + dim)  # q/k/v projections
        + dim * dim + dim  # proj
        + dim * mlp_ratio * dim + mlp_ratio * dim  # mlp up
        + mlp_ratio * dim * dim + dim  # mlp down
    )
    head = 2 * dim + dim * num_classes + num_classes  # ln_head + linear
    return patch_embed + pos + depth * per_block + head


@pytest.mark.parametrize("name,depth,dim,heads", [("vit_tiny", 12, 192, 3), pytest.param("vit_small", 12, 384, 6, marks=pytest.mark.slow)])
def test_param_count_matches_formula(name, depth, dim, heads):
    m = models.get_model(name)
    v = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32), train=False)
    n = sum(x.size for x in jax.tree_util.tree_leaves(v["params"]))
    assert n == _param_count(depth, dim, heads, patch=4, num_classes=100, tokens=64)
    assert "batch_stats" not in v  # transformer family is BN-free


def test_scanned_trunk_stacks_params():
    """The trunk is one nn.scan: every block param carries a (depth, ...)
    leading axis — the axis pipeline parallelism shards."""
    m = models.get_model("vit_tiny")
    v = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32), train=False)
    blocks = v["params"]["blocks"]
    for leaf in jax.tree_util.tree_leaves(blocks):
        assert leaf.shape[0] == 12


def test_vit_rejects_indivisible_heads():
    """dim % heads != 0 must fail with a config-level error, not an opaque
    reshape failure inside nn.scan (advisor r2)."""
    m = ViT(depth=2, dim=100, heads=3)
    with pytest.raises(ValueError, match="divisible by heads"):
        m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32), train=False)


def test_scan_unroll_preserves_forward():
    """Unrolling the trunk scan (the TPU-default fast path) is a pure
    scheduling change: identical params structure, identical logits."""
    kw = dict(depth=4, dim=32, heads=2, patch=8)
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3), jnp.float32)
    base = ViT(**kw)
    v = base.init(jax.random.key(0), x, train=False)
    out = base.apply(v, x, train=False)
    for unroll in (-1, 2):
        m = ViT(scan_unroll=unroll, **kw)
        assert jax.tree_util.tree_structure(
            m.init(jax.random.key(0), x, train=False)
        ) == jax.tree_util.tree_structure(v)
        np.testing.assert_allclose(
            np.asarray(m.apply(v, x, train=False)), np.asarray(out), atol=1e-6
        )


@pytest.mark.slow
def test_bf16_policy_keeps_params_and_logits_fp32():
    m = models.get_model("vit_tiny", dtype=jnp.bfloat16)
    v = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3), jnp.float32), train=False)
    assert all(
        x.dtype == jnp.float32 for x in jax.tree_util.tree_leaves(v["params"])
    )
    out = m.apply(v, jnp.zeros((2, 32, 32, 3), jnp.float32), train=False)
    assert out.shape == (2, 100) and out.dtype == jnp.float32


@pytest.mark.slow
def test_remat_preserves_forward():
    kw = dict(depth=2, dim=32, heads=2, patch=8)
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3), jnp.float32)
    base = ViT(**kw)
    v = base.init(jax.random.key(0), x, train=False)
    out = base.apply(v, x, train=False)
    out_r = ViT(remat=True, **kw).apply(v, x, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_r), atol=1e-6)


@pytest.mark.slow
def test_trainer_end_to_end_vit(tmp_path):
    """fit → validate → test through the scanned SPMD programs with an
    (empty) batch_stats collection."""
    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data",
            "--limit-examples", "256",
            "--batch-size", "64",
            "--epoch", "2",
            "--lr", "0.01",
            "--model", "vit_tiny",  # name only; tiny stand-in passed below
            "--ckpt-path", str(tmp_path),
        ],
    )
    t = Trainer(hp, model=ViT(depth=2, dim=32, heads=2, patch=8))
    version = t.fit()
    results = t.test()
    t.close()
    assert version == 0
    assert (tmp_path / "version-0" / "last.ckpt").exists()
    assert 0.0 <= results["test_top1"] <= results["test_top5"] <= 100.0
    assert np.isfinite(results["test_loss"])


def test_config_accepts_vit_models():
    hp = load_config("tpu", argv=["--model", "vit_small", "--synthetic-data"])
    assert hp.model == "vit_small"


@pytest.mark.slow
def test_format1_vit_checkpoint_rejected(tmp_path):
    """A packed-qkv-era (format < 3) ViT checkpoint must fail loudly with
    the format explanation, not a confusing structure mismatch."""
    from flax import serialization

    from distributed_training_comparison_tpu.train.checkpoint import (
        load_checkpoint,
        load_resume_state,
        save_checkpoint,
    )
    from distributed_training_comparison_tpu.train import (
        configure_optimizers,
        create_train_state,
    )

    class HP:
        lr = 0.1
        weight_decay = 1e-4
        lr_decay_step_size = 25
        lr_decay_gamma = 0.1

    model = ViT(depth=2, dim=32, heads=2, patch=8)
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(model, jax.random.key(0), tx)

    # current-format roundtrip works
    path = save_checkpoint(tmp_path, state, epoch=0, val_acc=1.0)
    load_checkpoint(path, state)

    # strip the fmt field → format-1 file → must be rejected for ViT
    raw = serialization.msgpack_restore(path.read_bytes())
    del raw["fmt"]
    old = tmp_path / "old.ckpt"
    old.write_bytes(serialization.msgpack_serialize(raw))
    with pytest.raises(ValueError, match="format-1 ViT"):
        load_checkpoint(old, state)
    fake_last = tmp_path / "last.ckpt"
    fake_last.write_bytes(
        serialization.msgpack_serialize(
            {"fmt": 2, "state": {}, "epoch": 0, "best_acc": 0.0}
        )
    )
    with pytest.raises(ValueError, match="format-2 ViT"):
        load_resume_state(fake_last, state)


@pytest.mark.slow
def test_trainer_plumbs_image_size_to_vit(tmp_path):
    """--image-size must reach the ViT's position embedding (it is sized in
    setup(), unlike the resolution-agnostic ResNets)."""
    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data",
            "--image-size", "64",
            "--limit-examples", "128",
            "--batch-size", "32",
            "--model", "vit_tiny",
            "--ckpt-path", str(tmp_path),
        ],
    )
    t = Trainer(hp)
    tokens = (64 // t.model.patch) ** 2
    assert t.model.image_size == 64
    assert t.state.params["pos_emb"].shape == (1, tokens, t.model.dim)
    losses, _ = t._train_epoch_device(0)  # one epoch at 64px runs
    assert np.all(np.isfinite(np.asarray(losses)))
    t.close()


def test_fused_gate_declines_over_vmem_budget_with_warning():
    """A block whose static weight footprint exceeds the VMEM budget must
    compose even under 'force' (ADVICE r5 #2) — and 'force' being declined
    must warn once, naming the condition (ADVICE r5 #3)."""
    from distributed_training_comparison_tpu.models import vit as vit_mod
    from distributed_training_comparison_tpu.ops.vmem import (
        fits_weight_budget,
        fused_block_weight_bytes,
    )

    # vit_tiny dims stay under budget (the kernel's measured win regime
    # must keep its fast path); dim-384 blocks exceed it
    assert fits_weight_budget(fused_block_weight_bytes(192, 4, jnp.bfloat16))
    assert not fits_weight_budget(fused_block_weight_bytes(384, 4, jnp.bfloat16))

    vit_mod._FUSION_FORCE_WARNED.clear()
    block = vit_mod.ViTBlock(dim=384, heads=6, block_fusion="force")
    x = jnp.zeros((1, 256, 384))  # inside the 128-512 token window
    with pytest.warns(UserWarning, match="VMEM weight footprint"):
        block.init(jax.random.key(0), x)


def test_force_decline_warns_outside_token_window():
    from distributed_training_comparison_tpu.models import vit as vit_mod

    vit_mod._FUSION_FORCE_WARNED.clear()
    block = vit_mod.ViTBlock(dim=64, heads=2, block_fusion="force")
    with pytest.warns(UserWarning, match="outside the measured 128-512"):
        block.init(jax.random.key(0), jnp.zeros((1, 64, 64)))
    # one-time: a second trace of the same declined reason stays silent
    with _no_user_warnings():
        block.init(jax.random.key(1), jnp.zeros((1, 64, 64)))


import contextlib


@contextlib.contextmanager
def _no_user_warnings():
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error", UserWarning)
        yield
