"""Sequence/context parallelism vs full attention on the 8-device CPU mesh.

Ring attention and Ulysses all-to-all must be *exact*: the sequence axis is
sharded over mesh devices, yet outputs and all three gradients match a
single-device full-attention reference to fp32 tolerance — causal and not.
The reference repo has nothing to compare against here (no sequence axis
anywhere, SURVEY.md §2.2); the contract is mathematical equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_comparison_tpu.ops import mha_reference
from distributed_training_comparison_tpu.parallel import (
    make_mesh,
    make_ring_attention,
    make_ulysses_attention,
)

pytestmark = pytest.mark.slow  # multi-process / heavy-compile: full-suite only

B, H, S, D = 4, 8, 256, 32


@pytest.fixture(scope="module")
def qkv():
    kq, kk, kv, kdo = jax.random.split(jax.random.key(0), 4)
    return (
        jax.random.normal(kq, (B, H, S, D), jnp.float32),
        jax.random.normal(kk, (B, H, S, D), jnp.float32),
        jax.random.normal(kv, (B, H, S, D), jnp.float32),
        jax.random.normal(kdo, (B, H, S, D), jnp.float32),
    )


@pytest.fixture(scope="module", params=[(2, 4), (1, 8)], ids=["mesh2x4", "mesh1x8"])
def mesh(request):
    data, model = request.param
    return make_mesh(8, model)


@pytest.mark.parametrize("maker", [make_ring_attention, make_ulysses_attention],
                         ids=["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(mesh, qkv, maker, causal):
    q, k, v, _ = qkv
    with jax.default_matmul_precision("highest"):
        full = mha_reference(q, k, v, causal=causal)
        out = maker(mesh, causal=causal)(q, k, v)
    assert float(jnp.max(jnp.abs(out - full))) < 1e-5


@pytest.mark.parametrize("maker", [make_ring_attention, make_ulysses_attention],
                         ids=["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_full_attention(mesh, qkv, maker, causal):
    q, k, v, do = qkv
    sp = maker(mesh, causal=causal)
    with jax.default_matmul_precision("highest"):
        g_sp = jax.grad(
            lambda q, k, v: (sp(q, k, v) * do).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        g_full = jax.grad(
            lambda q, k, v: (mha_reference(q, k, v, causal=causal) * do).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
    for a, b, name in zip(g_sp, g_full, "qkv"):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-4, f"d{name}"


def test_ring_preserves_dtype_and_sharding(qkv):
    mesh = make_mesh(8, 4)
    q, k, v, _ = qkv
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = make_ring_attention(mesh)(qb, kb, vb)
    assert out.dtype == jnp.bfloat16 and out.shape == (B, H, S, D)
    # output stays sequence-sharded (the memory point of the exercise):
    # each device holds S/4 rows of the sequence, B/2 of the batch
    assert not out.sharding.is_fully_replicated
    assert {s.data.shape for s in out.addressable_shards} == {(B // 2, H, S // 4, D)}


def test_ulysses_rejects_indivisible_heads(qkv):
    mesh = make_mesh(8, 8)  # seq axis 8; H=8 ok — build a 3-head input
    q = jnp.zeros((2, 3, S, D), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        make_ulysses_attention(mesh)(q, q, q)


@pytest.mark.parametrize("seq_impl", ["ring", "ulysses"])
def test_sequence_vit_apply_matches_direct(seq_impl):
    """The sequence-parallel trunk (tokens sharded across the model axis,
    attention via ring/ulysses through the block's attn_impl dispatch) is
    the same function as the direct apply — gradients included."""
    from distributed_training_comparison_tpu.models import ViT
    from distributed_training_comparison_tpu.parallel import sequence_vit_apply

    mesh = make_mesh(8, 4)
    model = ViT(depth=4, dim=32, heads=4, patch=4)  # 64 tokens / 4 shards
    x = jax.random.normal(jax.random.key(1), (8, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    with jax.default_matmul_precision("highest"):
        direct = model.apply(variables, x, train=False)
        out = sequence_vit_apply(model, variables, x, mesh, seq_impl=seq_impl)
        assert float(jnp.max(jnp.abs(direct - out))) < 1e-5
        g_direct = jax.grad(
            lambda v: (model.apply(v, x, train=False) ** 2).mean()
        )(variables)
        g_seq = jax.grad(
            lambda v: (
                sequence_vit_apply(model, v, x, mesh, seq_impl=seq_impl) ** 2
            ).mean()
        )(variables)
    worst = max(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(
                lambda a, b: float(jnp.max(jnp.abs(a - b))), g_direct, g_seq
            )
        )
    )
    assert worst < 1e-5


def test_sequence_vit_apply_validates_divisibility():
    from distributed_training_comparison_tpu.models import ViT
    from distributed_training_comparison_tpu.parallel import sequence_vit_apply

    mesh = make_mesh(8, 4)
    x = jnp.zeros((8, 32, 32, 3), jnp.float32)
    model = ViT(depth=2, dim=32, heads=2, patch=4)  # 64 tokens, heads=2 < 4
    v = model.init(jax.random.key(0), x, train=False)
    with pytest.raises(ValueError, match="heads"):
        sequence_vit_apply(model, v, x, mesh, seq_impl="ulysses")


def test_trainer_sequence_style_matches_baseline(tmp_path):
    """One epoch under --parallel-style sequence reproduces the unsharded
    loss trajectory (same seed, same data)."""
    import numpy as np

    from distributed_training_comparison_tpu.config import load_config
    from distributed_training_comparison_tpu.models import ViT
    from distributed_training_comparison_tpu.train import Trainer

    def fit_losses(extra, tag):
        hp = load_config(
            "tpu",
            argv=[
                "--synthetic-data",
                "--limit-examples", "256",
                "--batch-size", "64",
                "--epoch", "1",
                "--lr", "0.01",
                "--ckpt-path", str(tmp_path / tag),
                *extra,
            ],
        )
        t = Trainer(hp, model=ViT(depth=4, dim=32, heads=4, patch=4))
        losses, _ = t._train_epoch_device(0)
        out = np.asarray(losses)
        t.close()
        return out

    with jax.default_matmul_precision("highest"):
        base = fit_losses([], "base")
        seq = fit_losses(
            ["--model-parallel", "4", "--parallel-style", "sequence"], "seq"
        )
    np.testing.assert_allclose(seq, base, atol=5e-4)


def test_sequence_composes_with_grad_accum():
    """SP x grad-accum: sequence-sharded trunk under 2 sequential
    micro-batches matches the unsharded single-shot update exactly."""
    import numpy as np

    from distributed_training_comparison_tpu.models import ViT
    from distributed_training_comparison_tpu.parallel import (
        make_sequence_apply_fn,
        replicated_sharding,
        shard_batch,
    )
    from distributed_training_comparison_tpu.train import (
        configure_optimizers,
        create_train_state,
        make_train_step,
    )

    class HP:
        lr = 0.1
        weight_decay = 1e-4
        lr_decay_step_size = 25
        lr_decay_gamma = 0.1

    model = ViT(depth=4, dim=32, heads=4, patch=4)
    rng = np.random.default_rng(5)
    images = rng.integers(0, 255, size=(64, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 100, size=(64,), dtype=np.int32)

    results = {}
    with jax.default_matmul_precision("highest"):
        for tag, mp, accum in (("base", 1, 1), ("sp+accum", 4, 2)):
            mesh = make_mesh(8, mp)
            tx, _ = configure_optimizers(HP, steps_per_epoch=4)
            state = create_train_state(model, jax.random.key(0), tx)
            if mp > 1:
                state = state.replace(
                    apply_fn=make_sequence_apply_fn(model, mesh)
                )
            state = jax.device_put(state, replicated_sharding(mesh))
            step = make_train_step(mesh, augment=False, grad_accum=accum)
            bx, by = shard_batch((images, labels), mesh)
            new_state, metrics = step(state, bx, by, jax.random.key(1))
            results[tag] = (
                jax.device_get(new_state.params), float(metrics["loss"])
            )
    (p_base, l_base), (p_sp, l_sp) = results["base"], results["sp+accum"]
    assert abs(l_base - l_sp) < 1e-5 * max(1.0, abs(l_base))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6),
        p_base,
        p_sp,
    )


def test_ring_jits_under_jit(qkv):
    """The shard_map'd ring composes with an outer jit (how a train step
    would embed it)."""
    mesh = make_mesh(8, 4)
    q, k, v, _ = qkv
    ring = make_ring_attention(mesh, causal=True)
    with jax.default_matmul_precision("highest"):
        out_jit = jax.jit(ring)(q, k, v)
        out_eager = ring(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_jit), np.asarray(out_eager), atol=1e-6
    )
