"""Optimizer parity tests: optax chain vs torch SGD semantics.

The SURVEY.md §7 risk list calls out exact torch SGD(nesterov, wd-coupled)
+ StepLR parity as accuracy-critical; these tests verify it numerically
against torch (CPU build available in the image) rather than by reading
formulas.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
import torch

from distributed_training_comparison_tpu.train.optim import (
    configure_optimizers,
    step_lr_schedule,
)


class HP:
    lr = 0.1
    weight_decay = 1e-4
    lr_decay_step_size = 2
    lr_decay_gamma = 0.1


def test_step_lr_staircase():
    sched = step_lr_schedule(0.1, step_size_epochs=25, gamma=0.1, steps_per_epoch=100)
    assert float(sched(0)) == pytest.approx(0.1)
    assert float(sched(2499)) == pytest.approx(0.1)
    assert float(sched(2500)) == pytest.approx(0.01)
    assert float(sched(4999)) == pytest.approx(0.01)
    assert float(sched(5000)) == pytest.approx(0.001)


def test_sgd_matches_torch_nesterov_wd():
    """Run 7 identical steps in torch and optax from the same init/grads and
    compare parameters (covers momentum warmup + an LR decay boundary)."""
    rng = np.random.default_rng(0)
    w0 = rng.normal(size=(5, 3)).astype(np.float32)
    grads = [rng.normal(size=(5, 3)).astype(np.float32) for _ in range(7)]

    # torch: StepLR steps per epoch; emulate 1 epoch == 2 optimizer steps
    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.SGD(
        [tw], lr=HP.lr, momentum=0.9, nesterov=True, weight_decay=HP.weight_decay
    )
    sched = torch.optim.lr_scheduler.StepLR(
        opt, step_size=HP.lr_decay_step_size, gamma=HP.lr_decay_gamma
    )
    steps_per_epoch = 2
    for i, g in enumerate(grads):
        opt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        opt.step()
        if (i + 1) % steps_per_epoch == 0:
            sched.step()

    # ours: schedule over global steps with the same steps_per_epoch
    tx, _ = configure_optimizers(HP, steps_per_epoch=steps_per_epoch)
    params = {"w": jnp.asarray(w0)}
    opt_state = tx.init(params)
    for g in grads:
        updates, opt_state = tx.update({"w": jnp.asarray(g)}, opt_state, params)
        params = optax.apply_updates(params, updates)

    np.testing.assert_allclose(
        np.asarray(params["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6
    )


def test_weight_decay_applies_to_all_params():
    """torch SGD decays every param incl. BN scale/bias; the chain must not
    mask anything."""
    tx, _ = configure_optimizers(HP, steps_per_epoch=1)
    params = {"conv": jnp.ones((2, 2)), "bn_scale": jnp.ones((4,))}
    zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    updates, _ = tx.update(zero_grads, tx.init(params), params)
    # with zero grads, first-step nesterov update = -lr * (1+m) * wd * param
    for leaf in jax.tree_util.tree_leaves(updates):
        np.testing.assert_allclose(
            np.asarray(leaf), -HP.lr * 1.9 * HP.weight_decay, rtol=1e-5
        )
