"""Eager-parity rail tests (parity/ subsystem, ISSUE 16).

Three layers, mirroring the rail's own architecture:

- **unit** — the diff engine's pure parts: tolerance/corrupt-spec parsing,
  the scale-aware ulp metric's edge lattice, the leaf-bisection search,
  the bit-flip injector;
- **engine** — the bitwise replay-family contract on the 8-device mesh:
  a K=4 chunked dispatch and four K=1 replay dispatches of the SAME
  scanned executable family must carry bit-identical state (this is the
  identity the replay gate's "always bitwise" claim stands on);
- **trainer** — ``--parity-check`` end to end through ``Trainer.fit``:
  green captures in both data modes, an injected ``--parity-corrupt``
  bit flip localized to exactly (step, stage, leaf) by the rendered
  ``run_report --parity`` view, and the fp16/int8 wire tiers passing
  under a calibrated ``ulp=K`` while failing under ``bitwise`` — the
  contrast that proves the tolerance axis measures something real.

The full-Trainer layout sweeps are slow-marked; the unit/engine subset
and one end-to-end green + one localization run stay in tier-1.
"""

import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))

from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.parallel import (
    make_mesh,
    replicated_sharding,
)
from distributed_training_comparison_tpu.parity import (
    Tolerance,
    checksum_state,
    corrupt_bitflip,
    f32_bits,
    parse_corrupt,
    ulp_distance,
)
from distributed_training_comparison_tpu.parity.diff import (
    _INT_DIVERGED,
    _first_divergent_leaf,
)
from distributed_training_comparison_tpu.data import synthetic_dataset
from distributed_training_comparison_tpu.train import (
    Trainer,
    configure_optimizers,
    create_train_state,
    make_chunk_runner,
)
from distributed_training_comparison_tpu.train.step import make_replay_step

from test_train import HP, TinyNet

pytestmark = pytest.mark.parity


# ------------------------------------------------------------------ unit


def test_tolerance_parse_and_exceeded():
    assert Tolerance.parse("bitwise").mode == "bitwise"
    t = Tolerance.parse("ulp=64")
    assert (t.mode, t.ulp) == ("ulp", 64)
    assert str(t) == "ulp=64"
    for bad in ("ulp=", "ulp=-1", "ulp=abc", "exact", ""):
        with pytest.raises(ValueError):
            Tolerance.parse(bad)
    bw = Tolerance.parse("bitwise")
    assert not bw.exceeded(0.0)
    assert bw.exceeded(0.5)  # zero-sign/NaN-payload diff: not bit-equal
    assert not t.exceeded(64.0)
    assert t.exceeded(64.1)
    assert t.exceeded(None)  # incomparable shapes always violate


def test_parse_corrupt():
    assert parse_corrupt("3:12:Dense") == (3, 12, "Dense")
    assert parse_corrupt("0:31:kernel:with:colons") == (
        0, 31, "kernel:with:colons"
    )
    for bad in ("3:32:Dense", "-1:0:Dense", "x:1:Dense", "3:1:", "3", "3:1"):
        with pytest.raises(ValueError):
            parse_corrupt(bad)


def test_ulp_distance_edge_lattice():
    one = np.float32([1.0, 2.0])
    assert ulp_distance(one, one.copy()) == 0.0
    next_up = one.copy()
    next_up[0] = np.nextafter(np.float32(1.0), np.float32(2.0))
    # adjacent representables at half the tensor scale: spacing(1.0) is
    # half an ulp at scale 2.0, so the scale-aware distance is 0.5
    assert 0.0 < ulp_distance(one, next_up) <= 1.0
    # exact bit equality is the ONLY zero: -0.0 vs 0.0 returns 0.5
    assert ulp_distance(np.float32([0.0]), np.float32([-0.0])) == 0.5
    # NaN placement mismatch is incomparable-bad
    assert ulp_distance(np.float32([np.nan]), np.float32([1.0])) == float("inf")
    # matching NaN placement compares the finite rest
    assert ulp_distance(
        np.float32([np.nan, 1.0]), np.float32([np.nan, 1.0])
    ) == 0.0
    # inf sign mismatch is incomparable-bad
    assert ulp_distance(
        np.float32([np.inf]), np.float32([-np.inf])
    ) == float("inf")
    # non-float leaves are exact
    assert ulp_distance(np.int32([5]), np.int32([5])) == 0.0
    assert ulp_distance(np.int32([5]), np.int32([6])) == _INT_DIVERGED
    # incomparable shapes
    assert ulp_distance(np.zeros(3, np.float32), np.zeros(4, np.float32)) is None


def test_ulp_distance_is_scale_aware():
    """A sign flip at the noise floor must price as sub-ulp noise, not as
    millions of lexicographic ulps — the dp=8 reduction-order case."""
    a = np.float32([1.0, 1e-12])
    b = np.float32([1.0, -1e-12])
    d = ulp_distance(a, b)
    assert d is not None and 0 < d < 1.0


def test_first_divergent_leaf_bisection():
    rec = np.arange(10, dtype=np.int64)
    assert _first_divergent_leaf(rec, rec.copy()) is None
    rep = rec.copy()
    rep[7] += 1
    assert _first_divergent_leaf(rec, rep) == 7
    rep[3] += 1  # multiple divergent leaves: names the FIRST
    assert _first_divergent_leaf(rec, rep) == 3
    rep2 = rec.copy()
    rep2[0] += 1
    assert _first_divergent_leaf(rec, rep2) == 0
    rep3 = rec.copy()
    rep3[9] += 1
    assert _first_divergent_leaf(rec, rep3) == 9


def test_f32_bits():
    assert f32_bits(1.0) == 0x3F800000
    assert f32_bits(np.float32(-0.0)) == 0x80000000


def test_corrupt_bitflip_flips_one_bit_of_first_match():
    state = {
        "params": {
            "Conv_0": {"kernel": jnp.ones((3,), jnp.float32)},
            "Dense_0": {"bias": jnp.full((4,), 2.0, jnp.float32)},
        }
    }
    out, path = corrupt_bitflip(state, "Dense", 31)  # sign bit
    assert "Dense_0" in path
    bias = np.asarray(out["params"]["Dense_0"]["bias"])
    assert bias[0] == -2.0 and np.all(bias[1:] == 2.0)
    np.testing.assert_array_equal(
        np.asarray(out["params"]["Conv_0"]["kernel"]), 1.0
    )
    with pytest.raises(ValueError):
        corrupt_bitflip(state, "NoSuchLeaf", 0)


def test_config_rejects_bad_parity_flags():
    base = ["--synthetic-data", "--limit-examples", "64", "--batch-size", "8"]
    with pytest.raises(SystemExit):
        load_config("ddp", argv=base + ["--parity-check", "2",
                                        "--parity-tol", "exact"])
    with pytest.raises(SystemExit):
        load_config("ddp", argv=base + ["--parity-corrupt", "1:2:Dense"])
    with pytest.raises(SystemExit):
        load_config("ddp", argv=base + ["--parity-check", "-1"])


# ---------------------------------------------------------------- engine


def test_replay_family_bitwise_matches_chunked_run():
    """The replay gate's foundation: one K=4 chunked dispatch and four
    K=1 dispatches of ``make_replay_step`` (same scanned executable
    family, ``donate=False``) must produce a bit-identical carried state
    — the runners' pinned any-chunking contract, observed through the
    same checksum walk the gate uses."""
    mesh = make_mesh(backend="ddp")
    x, y = synthetic_dataset(128, num_classes=10, seed=0)
    imgs = jnp.asarray(x).reshape(4, 32, *x.shape[1:])
    lbls = jnp.asarray(y).reshape(4, 32)
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state0 = jax.device_put(
        create_train_state(TinyNet(), jax.random.key(0), tx),
        replicated_sharding(mesh),
    )
    epoch_key = jax.random.fold_in(jax.random.key(1), 0)

    runner = make_chunk_runner(mesh, donate=False)
    chunked, _ = runner(state0, imgs, lbls, epoch_key, jnp.asarray(0))

    replay = make_replay_step(mesh)
    s = state0
    for k in range(4):
        s, metrics = replay(s, imgs[k], lbls[k], epoch_key, k)
        assert metrics["loss"].shape == ()
    np.testing.assert_array_equal(checksum_state(chunked), checksum_state(s))


# --------------------------------------------------------------- trainer


def _fit_parity(tmp_path, extra, model=None):
    """One Trainer.fit with the parity rail on; returns the single emitted
    ``parity`` event payload."""
    hp = load_config(
        "ddp",
        argv=[
            "--synthetic-data", "--limit-examples", "256",
            "--batch-size", "64", "--epoch", "1",
            "--eval-step", "10000", "--lr", "0.05",
            "--no-progress", "--save-last-min-secs", "0",
            "--ckpt-path", str(tmp_path),
            *extra,
        ],
    )
    t = Trainer(hp, model=model if model is not None else TinyNet(num_classes=100))
    try:
        t.fit()
    finally:
        t.close()
    payloads = []
    for p in Path(tmp_path).rglob("events*.jsonl"):
        for line in p.read_text().splitlines():
            ev = json.loads(line)
            if ev.get("kind") == "parity":
                payloads.append(ev["payload"])
    assert len(payloads) == 1, f"expected one parity event, got {payloads}"
    return payloads[0]


def test_trainer_parity_host_mode_green(tmp_path):
    p = _fit_parity(tmp_path, ["--data-mode", "host", "--parity-check", "3"])
    assert p["steps"] == 3 and p["mode"] == "host"
    assert p["replay"] == "ok" and p["replay_divergence"] is None
    assert p["eager_reference"] == "ok" and p["reference_divergence"] is None
    assert p["verdict"] == "ok"
    assert p["max_ulp"] <= 1024  # the calibrated dp-fp32 band
    assert p["layout"]["dp"] == 8 and not p["layout"]["zero"]

    import run_report

    assert run_report.parity_report(tmp_path, out=lambda s: None) == 0


def test_trainer_parity_corruption_localized(tmp_path):
    """The acceptance criterion: a single injected bit flip must come back
    from ``run_report --parity`` as exactly (step, stage, leaf)."""
    p = _fit_parity(
        tmp_path,
        ["--data-mode", "host", "--parity-check", "3",
         "--parity-corrupt", "1:6:Dense"],
    )
    assert p["verdict"] == "divergent" and p["replay"] == "divergent"
    rdiv = p["replay_divergence"]
    assert rdiv["step"] == 1
    assert rdiv["stage"] == "relayout"  # a params leaf: the final apply
    assert "Dense" in rdiv["leaf"]
    assert rdiv["divergent_leaves"] == 1
    assert p["corrupt"]["step"] == 1 and p["corrupt"]["bit"] == 6
    # the eager reference tracks the CLEAN replay, and a low mantissa bit
    # sits inside the fp32 fusion band — only the bitwise gate can see it
    assert p["eager_reference"] == "ok"

    import run_report

    lines = []
    assert run_report.parity_report(tmp_path, out=lines.append) == 1
    text = "\n".join(str(l) for l in lines)
    assert "DIVERGENT at step 1" in text
    assert "relayout X" in text  # the rendered bisection trail
    assert "Dense" in text


def test_run_report_parity_return_codes(tmp_path):
    import run_report

    empty = tmp_path / "empty"
    empty.mkdir()
    assert run_report.parity_report(empty, out=lambda s: None) == 2
    no_parity = tmp_path / "plain"
    no_parity.mkdir()
    (no_parity / "events.jsonl").write_text(
        json.dumps({"v": 1, "kind": "epoch_end", "t_wall": 0.0,
                    "epoch": 0, "payload": {"train_loss": 1.0}}) + "\n"
    )
    assert run_report.parity_report(no_parity, out=lambda s: None) == 0


@pytest.mark.slow
def test_trainer_parity_device_mode_green(tmp_path):
    p = _fit_parity(tmp_path, ["--data-mode", "device", "--parity-check", "3"])
    assert p["mode"] == "device" and p["verdict"] == "ok"
    assert p["replay"] == "ok" and p["eager_reference"] == "ok"
    assert p["max_ulp"] <= 1024


@pytest.mark.slow
def test_trainer_parity_fp16_wire_contrast(tmp_path):
    """The wire-tier contrast: the SAME fp16 capture passes under its
    calibrated ulp tolerance and fails under ``bitwise`` — the replay
    gate stays green both times (compression is deterministic; only the
    eager-vs-compiled quantize boundary reassociates)."""
    loose = _fit_parity(
        tmp_path / "loose",
        ["--data-mode", "host", "--parity-check", "3",
         "--grad-comms", "fp16", "--parity-tol", f"ulp={1 << 27}"],
    )
    assert loose["verdict"] == "ok" and loose["replay"] == "ok"
    assert loose["max_ulp"] > 1024  # quantize buckets flip: far off fp32 band
    assert loose["layout"]["wire"] == "fp16"

    strict = _fit_parity(
        tmp_path / "strict",
        ["--data-mode", "host", "--parity-check", "3",
         "--grad-comms", "fp16", "--parity-tol", "bitwise"],
    )
    assert strict["replay"] == "ok"  # bitwise replay is tol-independent
    assert strict["eager_reference"] == "divergent"
    assert strict["verdict"] == "divergent"
    assert strict["reference_divergence"]["ulp"] is not None


@pytest.mark.slow
def test_trainer_parity_int8_wire_under_calibrated_ulp(tmp_path):
    p = _fit_parity(
        tmp_path,
        ["--data-mode", "host", "--parity-check", "3",
         "--grad-comms", "int8", "--parity-tol", f"ulp={1 << 27}"],
    )
    assert p["verdict"] == "ok" and p["replay"] == "ok"
    assert p["max_ulp"] > 10  # real quantize noise, not a vacuous pass
    assert p["layout"]["wire"] == "int8"


@pytest.mark.slow
def test_trainer_parity_wire_true_pipeline_reference_unsupported(tmp_path):
    """The documented hole: the wire-true compressed pipeline keeps its
    error-feedback residual inside the schedule, which the eager rail
    does not model — the reference gate must say so explicitly while the
    bitwise replay gate still runs (and stays green)."""
    from distributed_training_comparison_tpu.models.vit import ViT

    p = _fit_parity(
        tmp_path,
        ["--data-mode", "device", "--parity-check", "2",
         "--model-parallel", "2", "--parallel-style", "pipeline",
         "--pipeline-schedule", "1f1b",
         "--pipeline-microbatches", "2", "--grad-comms", "fp16"],
        model=ViT(depth=8, dim=32, heads=2, patch=8),
    )
    assert p["replay"] == "ok"
    assert p["eager_reference"] == "unsupported"
    assert "wire" in p["eager_reference_reason"].lower()
    assert p["verdict"] == "ok"  # an unsupported reference is not a failure
