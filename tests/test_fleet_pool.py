"""Elastic fleet supervision tests (ISSUE 10): host pool + re-rendered
world size, resharded resume validation, and the satellites that ride
along (adaptive fleet-watcher poll, corrupt-shard quarantine, per-host
partial desync fingerprints, resize reporting).

The load-bearing properties pinned here:

- a host killed by a signal the supervisor did NOT send leaves the pool;
  the next attempt re-renders ``--world-size``/``--rank``/``--dist-url``
  from the survivors and a ``resize`` event prices the shrink;
- a returned host (``fleet/host-i.up``) triggers a deliberate
  drain-checkpoint-and-re-expand whose attempt never consumes the
  restart budget;
- when no legal world size exists the supervisor refuses with the actual
  numbers (batch, widths, nearest legal batches) — never a bare
  divisibility traceback, and never a doomed launch;
- a rollback replay under ``--health-quarantine`` excludes exactly the
  condemned batch window's examples, deterministically, with every other
  batch bit-identical;
- the per-host partial fingerprint matrix is constant down the data axis
  for a healthy sharded state, and any injected drift inside a model
  shard is caught — the case the post-collective scalar check erases.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import serialization

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))

import goodput_report  # noqa: E402
import run_report  # noqa: E402

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.data.loader import (
    DeviceDataset,
    HostLoader,
    PrefetchLoader,
)
from distributed_training_comparison_tpu.health import (
    HealthConfig,
    Watchdog,
    check_partial_desync,
    partial_fingerprints,
)
from distributed_training_comparison_tpu.obs.bus import EventBus
from distributed_training_comparison_tpu.obs.heartbeat import (
    FleetWatcher,
    LivenessTracker,
)
from distributed_training_comparison_tpu.parallel import make_mesh
from distributed_training_comparison_tpu.parallel.mesh import elastic_mesh_shape
from distributed_training_comparison_tpu.resilience import (
    EXIT_PREEMPTED,
    FleetPlanError,
    FleetSupervisor,
    ReshardError,
    aggregate_goodput,
    divisibility_help,
    read_manifest,
    validate_reshard,
    widest_legal_world,
)
from distributed_training_comparison_tpu.resilience.fleet import strip_flags

WORKER = Path(__file__).parent / "fleet_pool_worker.py"


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    monkeypatch.delenv(obs.RUN_ID_ENV, raising=False)
    monkeypatch.delenv(obs.ATTEMPT_ENV, raising=False)
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------- world render


def test_strip_flags_both_forms():
    args = [
        "train.py", "--world-size", "4", "--epoch", "3",
        "--dist-url=127.0.0.1:1", "--rank", "2", "--fleet-hosts=2",
    ]
    out = strip_flags(
        args, ("--world-size", "--rank", "--dist-url", "--fleet-hosts")
    )
    assert out == ["train.py", "--epoch", "3"]


def test_widest_legal_world_shrinks_for_divisibility():
    # 3 hosts x 1 device: batch 32 does not split 3 ways -> widest is 2
    assert widest_legal_world(3, batch_size=32, local_devices=1) == 2
    assert widest_legal_world(2, batch_size=32, local_devices=1) == 2
    # the N/2 case the issue names: batch divisibility forces half width
    assert widest_legal_world(3, batch_size=8, local_devices=2) == 2
    # tensor parallelism: total devices must tile the model axis
    assert widest_legal_world(
        3, batch_size=32, local_devices=1, model_parallel=2
    ) == 2
    # nothing legal: odd batch never splits over 2 devices/host
    assert widest_legal_world(3, batch_size=7, local_devices=2) is None
    # unknown local device count degrades to host granularity
    assert widest_legal_world(4, batch_size=6, local_devices=0) == 3
    # ...and with a model axis it must DEGRADE, not refuse: 4-chip hosts
    # tile model_parallel 4 at any W, which assuming 1 device/host would
    # wrongly reject (the Trainer's validate_reshard stays the authority)
    assert widest_legal_world(
        2, batch_size=32, local_devices=0, model_parallel=4
    ) == 2


def test_elastic_mesh_shape_rederives_axes():
    assert elastic_mesh_shape(8, 2) == (4, 2, 1)
    assert elastic_mesh_shape(4, 1) == (4, 1, 1)
    assert elastic_mesh_shape(3, 2) is None  # devices don't tile the model axis
    assert elastic_mesh_shape(1, 2) is None  # model axis can't shrink below TP
    assert elastic_mesh_shape(0, 1) is None
    # the dedicated pipe axis joins the tiling rule: DP x TP x PP
    assert elastic_mesh_shape(8, 2, 2) == (2, 2, 2)
    assert elastic_mesh_shape(8, 1, 4) == (2, 1, 4)
    assert elastic_mesh_shape(4, 2, 2) == (1, 2, 2)
    assert elastic_mesh_shape(2, 2, 2) is None  # can't shrink below TPxPP
    assert elastic_mesh_shape(6, 2, 2) is None  # doesn't tile TPxPP


def test_divisibility_help_carries_actionable_numbers():
    msg = divisibility_help(32, 3, 1)
    assert "32" in msg and "3" in msg
    assert "[1, 2]" in msg            # legal widths for this batch
    assert "30" in msg and "33" in msg  # nearest legal batches at width 3


def test_validate_reshard_plan_and_refusal():
    mesh = make_mesh(backend="ddp")  # (8, 1, 1) on the test process's devices
    plan = validate_reshard(
        {"mesh": {"data": 4, "model": 1}, "devices": 4},
        mesh, batch_size=32,
    )
    assert plan["changed"] is True
    assert plan["saved_mesh"] == {"data": 4, "model": 1}
    assert plan["mesh"] == {"data": 8, "model": 1, "pipe": 1}
    assert plan["per_device_batch"] == 4
    same = validate_reshard(
        {"mesh": dict(mesh.shape), "devices": jax.device_count()},
        mesh, batch_size=32,
    )
    assert same["changed"] is False
    assert validate_reshard(None, mesh, batch_size=32)["changed"] is False
    with pytest.raises(ReshardError) as exc:
        validate_reshard({}, mesh, batch_size=30)
    assert "30" in str(exc.value) and "8" in str(exc.value)
    assert "nearest legal batch" in str(exc.value)


def test_trainer_batch_error_carries_legal_numbers(tmp_path):
    from distributed_training_comparison_tpu.train import Trainer

    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "64",
            "--batch-size", "36",  # 36 % 8 devices != 0
            "--ckpt-path", str(tmp_path), "--no-progress",
        ],
    )
    with pytest.raises(ValueError) as exc:
        Trainer(hp)
    assert "legal data-parallel sizes" in str(exc.value)
    assert "nearest legal batch sizes" in str(exc.value)


# --------------------------------------------------------- the host pool


class FakeProc:
    """A Popen-shaped child whose life is scripted: runs for ``runs_for``
    polls, then exits ``rc`` (None = runs until terminated)."""

    _next_pid = 5000

    def __init__(self, rc, runs_for=3):
        FakeProc._next_pid += 1
        self.pid = FakeProc._next_pid
        self._rc_final = rc
        self._runs_for = runs_for
        self._polls = 0
        self._rc = None
        self._terminated = False

    def poll(self):
        self._polls += 1
        if self._rc is None:
            if self._terminated:
                self._rc = EXIT_PREEMPTED
            elif self._rc_final is not None and self._polls > self._runs_for:
                self._rc = self._rc_final
        return self._rc

    def terminate(self):
        self._terminated = True

    def kill(self):
        self._rc = -9


def _fleet(tmp_path, scripts, events, **kw):
    """A FleetSupervisor over scripted fake children.  ``scripts`` is one
    list of FakeProc ctor args per spawn, in spawn order."""
    it = iter(scripts)

    def spawn(cmd, env):
        rc, runs_for = next(it)
        p = FakeProc(rc, runs_for)
        p.cmd = list(cmd)
        return p

    kw.setdefault("hosts", 2)
    kw.setdefault("batch_size", 32)
    kw.setdefault("local_devices", 1)
    kw.setdefault("grace_s", 0.0)
    kw.setdefault("poll_s", 0.05)
    return FleetSupervisor(
        ["train.py", "--epoch", "3"],
        ckpt_root=tmp_path,
        spawn=spawn,
        sleep=lambda s: None,
        log=lambda m: None,
        events=lambda kind, **p: events.append((kind, p)),
        **kw,
    )


def test_external_kill_shrinks_then_up_marker_reexpands(tmp_path):
    events: list = []
    # attempt 0: host 0 wedges (runs forever), host 1 dies by external -9
    # attempt 1: world 1 on host 0, runs until the deliberate drain
    # attempt 2: world 2 again, both exit 0
    scripts = [(None, 0), (-9, 1), (None, 0), (0, 2), (0, 2)]
    sup = _fleet(tmp_path, scripts, events)
    orig = sup._launch

    def launch(attempt):
        if attempt == 1:  # host 1 "returns" mid-attempt
            sup._marker(1, "up").write_text("")
        return orig(attempt)

    sup._launch = launch
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert [
        (r["from_world"], r["to_world"], r["reason"])
        for r in summary["resizes"]
    ] == [(2, 1, "host_lost"), (1, 2, "host_returned")]
    assert summary["resizes"][0]["lost"] == [1]
    assert summary["resizes"][1]["returned"] == [1]
    assert summary["hosts"] == {"0": "alive", "1": "alive"}
    worlds = [
        p["world_size"] for k, p in events if k == "attempt_start"
    ]
    assert worlds == [2, 1, 2]
    hosts = [p["hosts"] for k, p in events if k == "attempt_start"]
    assert hosts == [[0, 1], [0], [0, 1]]
    kinds = [k for k, _ in events]
    assert kinds.count("resize") == 2
    # marker was consumed
    assert not sup._marker(1, "up").exists()


def test_deliberate_reexpand_drain_spares_budget(tmp_path):
    """max_restarts=1: attempt 0 ends by host loss (budget 1/1), attempt 1
    by the deliberate re-expand drain (free), attempt 2 completes — with a
    budget-consuming drain the run would have given up."""
    events: list = []
    scripts = [(None, 0), (-9, 1), (None, 0), (0, 2), (0, 2)]
    sup = _fleet(tmp_path, scripts, events, max_restarts=1)
    orig = sup._launch

    def launch(attempt):
        if attempt == 1:
            sup._marker(1, "up").write_text("")
        return orig(attempt)

    sup._launch = launch
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert "give_up" not in [k for k, _ in events]
    # the planned re-expand drain is not a preemption on the scoreboard:
    # only the host-loss attempt counts
    assert summary["preemptions"] == 1
    assert summary["planned_drains"] == 1


def test_supervisor_sigterm_death_is_not_host_loss(tmp_path):
    """A child that dies from the supervisor's OWN SIGTERM (or the grace
    SIGKILL) must not be marked lost: the supervisor killed the process,
    not the machine."""
    events: list = []
    # attempt 0: host 0 crashes rc=1; host 1 never drains -> grace SIGKILL
    # attempt 1 (after backoff): both exit 0 — world stays 2, no resize
    scripts = [(1, 1), (None, 0), (0, 2), (0, 2)]
    sup = _fleet(tmp_path, scripts, events)
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert summary["resizes"] == []
    assert summary["hosts"] == {"0": "alive", "1": "alive"}
    worlds = [p["world_size"] for k, p in events if k == "attempt_start"]
    assert worlds == [2, 2]


def test_down_marker_drains_and_shrinks(tmp_path):
    events: list = []
    # attempt 0: both run until the down marker triggers the drain
    # attempt 1: world 1 on host 0 completes
    scripts = [(None, 0), (None, 0), (0, 2)]
    sup = _fleet(tmp_path, scripts, events)
    orig = sup._launch

    def launch(attempt):
        if attempt == 0:
            sup._marker(1, "down").write_text("")
        return orig(attempt)

    sup._launch = launch
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert [
        (r["from_world"], r["to_world"], r["reason"])
        for r in summary["resizes"]
    ] == [(2, 1, "host_lost")]
    assert summary["hosts"]["1"] == "lost"


def test_down_marker_for_spare_host_does_not_drain(tmp_path):
    """batch 32 on 3 one-device hosts caps the legal world at 2, so host 2
    is an alive SPARE.  Marking it down changes pool membership but must
    not drain the running ranks or burn budget."""
    events: list = []
    scripts = [(0, 4), (0, 4)]  # ranks 0+1 run a while, then finish clean
    sup = _fleet(tmp_path, scripts, events, hosts=3)
    orig = sup._launch

    def launch(attempt):
        sup._marker(2, "down").write_text("")
        return orig(attempt)

    sup._launch = launch
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert len(summary["attempts"]) == 1  # nobody was drained
    assert summary["resizes"] == []
    assert summary["hosts"] == {"0": "alive", "1": "alive", "2": "lost"}


def test_spare_return_that_cannot_widen_does_not_drain(tmp_path):
    """batch 32 caps 3 one-device hosts at world 2: a spare (host 2)
    cycling down and back up can never widen the legal world, so its
    return must not burn a drain-checkpoint-relaunch cycle."""
    events: list = []
    scripts = [(0, 6), (0, 6)]
    sup = _fleet(tmp_path, scripts, events, hosts=3)
    sup._marker(2, "down").write_text("")  # spare lost before launch
    orig = sup._launch

    def launch(attempt):
        sup._marker(2, "up").write_text("")  # returns mid-attempt
        return orig(attempt)

    sup._launch = launch
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert len(summary["attempts"]) == 1  # no drain fired
    assert summary["hosts"]["2"] == "alive"  # but the pool took it back


def test_crash_during_deliberate_drain_keeps_crash_semantics(tmp_path):
    """A rank that CRASHES while draining for a planned re-expand must not
    be laundered into a budget-free planned drain."""

    class CrashOnDrain(FakeProc):
        def terminate(self):
            self._rc = 1  # the drain's checkpoint write blew up

    events: list = []
    procs = iter([CrashOnDrain(None, 0), FakeProc(0, 2), FakeProc(0, 2)])
    sup = FleetSupervisor(
        ["train.py"], hosts=2, ckpt_root=tmp_path, batch_size=32,
        local_devices=1, grace_s=0.0, poll_s=0.05,
        spawn=lambda c, e: next(procs),
        sleep=lambda s: None, log=lambda m: None,
        events=lambda kind, **p: events.append((kind, p)),
    )
    sup._marker(1, "down").write_text("")  # world 1 on host 0
    orig = sup._launch

    def launch(attempt):
        if attempt == 0:
            sup._marker(1, "up").write_text("")  # triggers the re-expand
        return orig(attempt)

    sup._launch = launch
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert summary["attempts"][0]["returncode"] == 1  # the crash, not 75
    assert summary["preemptions"] == 0
    assert summary["planned_drains"] == 0  # nothing was laundered


def test_pool_exhausted_readmits_everything(tmp_path):
    events: list = []
    scripts = [(0, 1), (0, 1)]
    sup = _fleet(tmp_path, scripts, events)
    sup._marker(0, "down").write_text("")
    sup._marker(1, "down").write_text("")
    summary = sup.run()  # both pre-marked down -> full re-admission
    assert summary["final_rc"] == 0
    assert [p["world_size"] for k, p in events if k == "attempt_start"] == [2]


def test_fleet_refuses_with_numbers_when_no_legal_world(tmp_path):
    events: list = []
    sup = _fleet(
        tmp_path, [], events, batch_size=7, local_devices=2,
    )
    with pytest.raises(FleetPlanError) as exc:
        sup.run()
    msg = str(exc.value)
    assert "7" in msg and "no legal world size" in msg
    assert "nearest legal batch sizes" in msg
    assert [k for k, _ in events] == ["give_up"]


def test_fleet_floor_refusal_names_the_floor_not_the_batch(tmp_path):
    """--fleet-min-hosts refusal: batch 32 divides width 1 fine — the
    message must name the floor, never fabricate a divisibility claim."""
    sup = _fleet(
        tmp_path, [], [], hosts=2, batch_size=32, local_devices=1,
        min_hosts=3,
    )
    with pytest.raises(FleetPlanError) as exc:
        sup.run()
    msg = str(exc.value)
    assert "floor 3" in msg and "widest legal world 2" in msg
    assert "not divisible" not in msg


def test_mid_run_refusal_stops_orderly_with_summary(tmp_path):
    """Losing a host mid-run until no legal world remains (model_parallel
    needs 2 devices, 1 one-device host survives) must end with a give_up
    event and a SUMMARY — not a traceback that loses the completed
    attempts' goodput aggregation."""
    events: list = []
    scripts = [(None, 0), (-9, 1)]  # host 1 dies externally; host 0 drained
    sup = _fleet(
        tmp_path, scripts, events, model_parallel=2, local_devices=1,
    )
    summary = sup.run()  # no exception: the refusal is orderly mid-run
    assert summary["final_rc"] == EXIT_PREEMPTED
    assert len(summary["attempts"]) == 1
    kinds = [k for k, _ in events]
    assert kinds == ["attempt_start", "attempt_end", "give_up"]
    assert "model_parallel 2" in events[-1][1]["reason"]


def test_render_cmd_re_renders_world_flags(tmp_path):
    sup = _fleet(tmp_path, [], [])
    cmd = sup._render_cmd(
        ["w.py", "--world-size", "9", "--rank", "3",
         "--dist-url=10.0.0.1:1", "--fleet-hosts", "2", "--epoch", "3"],
        world=2, rank=1, port=4567,
    )
    assert cmd == [
        "w.py", "--epoch", "3",
        "--world-size", "2", "--rank", "1", "--dist-url", "127.0.0.1:4567",
    ]


# ------------------------------------------- watcher + tracker satellites


def test_tracker_reset_expect_seeds_silent_hosts():
    tr = LivenessTracker(heartbeat_s=1.0)  # slow > 3s, dead > 10s
    tr.reset(expect=range(2), attempt=3, now=0.0)
    assert tr.check(now=2.0) == []  # young silence is fine
    findings = tr.check(now=20.0)
    # both expected hosts are silent past "dead", but neither ever beat:
    # the pre-first-beat cap holds them at "slow" (first-dispatch compile)
    assert [(f["process_index"], f["state"]) for f in findings] == [
        (0, "slow"), (1, "slow"),
    ]
    assert all(f["attempt"] == 3 for f in findings)
    tr.reset()
    assert tr.check(now=30.0) == []  # plain reset forgets the expectation


def test_fleet_watcher_adaptive_poll(tmp_path):
    bus = EventBus(run_id="ab" * 8)
    tr = LivenessTracker(heartbeat_s=1.0)
    w = FleetWatcher(tmp_path, bus, tracker=tr, poll_s=1.0)
    assert w.current_poll_s() == 1.0  # nothing tracked: steady cadence
    tr.observe({"kind": "heartbeat", "process_index": 0, "step": 1}, now=0.0)
    w.step(now=0.5)
    assert w.current_poll_s() == 1.0  # host healthy
    w.step(now=5.0)  # 5s stale -> slow
    assert tr.states()[0] == "slow"
    assert w.current_poll_s() == pytest.approx(0.1)  # tightened
    tr.observe({"kind": "heartbeat", "process_index": 0, "step": 2}, now=6.0)
    w.step(now=6.1)  # recovered
    assert w.current_poll_s() == 1.0


def test_fleet_watcher_fast_poll_never_exceeds_base(tmp_path):
    bus = EventBus(run_id="ab" * 8)
    w = FleetWatcher(
        tmp_path, bus, tracker=LivenessTracker(), poll_s=0.05
    )
    assert w.fast_poll_s == pytest.approx(0.05)


def test_fleet_poll_secs_flag_validation():
    hp = load_config("tpu", ["--synthetic-data"])
    assert hp.fleet_poll_secs == 1.0 and hp.fleet_hosts == 0
    with pytest.raises(SystemExit):
        load_config("tpu", ["--fleet-poll-secs", "0"])
    with pytest.raises(SystemExit):
        load_config("tpu", ["--fleet-hosts", "2"])  # needs --supervise
    with pytest.raises(SystemExit):
        load_config(
            "tpu",
            ["--supervise", "--fleet-hosts", "2", "--world-size", "2"],
        )
    hp = load_config("tpu", ["--supervise", "--fleet-hosts", "2"])
    assert hp.fleet_hosts == 2 and hp.fleet_local_devices == 0


# ------------------------------------------------ corrupt-shard quarantine


def _tiny_dataset(n=64):
    rng = np.random.default_rng(0)
    return DeviceDataset(
        rng.integers(0, 255, size=(n, 8, 8, 3)).astype(np.uint8),
        rng.integers(0, 100, size=(n,)).astype(np.int32),
    )


def test_loader_quarantine_substitutes_only_the_bad_window():
    ds = _tiny_dataset()
    loader = HostLoader(ds, 8, shuffle=True, drop_last=True, seed=3)
    before = loader._permutation(2)
    bad = loader.batch_example_indices(2, 1)
    assert len(bad) == 8
    added = loader.quarantine(bad)
    assert added == 8
    after = loader._permutation(2)
    # the condemned examples are gone
    assert not np.isin(after, bad).any()
    # and every untouched position is bit-identical
    untouched = ~np.isin(before, bad)
    np.testing.assert_array_equal(after[untouched], before[untouched])
    # deterministic: a fresh loader with the same quarantine agrees
    twin = HostLoader(ds, 8, shuffle=True, drop_last=True, seed=3)
    twin.quarantine(bad)
    np.testing.assert_array_equal(twin._permutation(2), after)
    # batch count unchanged (substitution, not shortening)
    assert len(after) == len(before)
    # re-quarantining is idempotent
    assert loader.quarantine(bad) == 0


def test_loader_quarantine_refuses_to_exclude_everything():
    ds = _tiny_dataset(8)
    loader = HostLoader(ds, 4, seed=1)
    kept = loader.quarantine(np.arange(2))
    assert kept == 2
    before = loader._permutation(0)
    with pytest.raises(ValueError, match="every example"):
        loader.quarantine(np.arange(8))
    # a refused quarantine leaves the loader EXACTLY as it was — the next
    # epoch's permutation must not see a half-applied set
    assert loader._quarantined == {0, 1}
    np.testing.assert_array_equal(loader._permutation(0), before)


def test_loader_quarantine_substitutes_stay_in_shard():
    """Under multi-host sharding the substitute pool is THIS loader's own
    slice of the epoch — drawing from the whole dataset would hand this
    host examples another host's shard also trains."""
    ds = _tiny_dataset(64)
    shards = [
        HostLoader(ds, 4, shuffle=True, drop_last=True, seed=9,
                   num_shards=2, shard=i)
        for i in (0, 1)
    ]
    epoch = 3
    own = shards[0]._permutation(epoch)
    other = set(shards[1]._permutation(epoch).tolist())
    assert not (set(own.tolist()) & other)  # shards start disjoint
    shards[0].quarantine(shards[0].batch_example_indices(epoch, 0))
    after = shards[0]._permutation(epoch)
    # substitutes were drawn from shard 0's own slice: still disjoint
    assert not (set(after.tolist()) & other)


def test_prefetch_loader_delegates_quarantine():
    ds = _tiny_dataset()
    pf = PrefetchLoader(HostLoader(ds, 8, seed=5), depth=1)
    ids = pf.batch_example_indices(0, 0)
    assert pf.quarantine(ids) == len(set(ids.tolist()))
    assert not np.isin(pf.loader._permutation(0), ids).any()
    pf.close()


def test_watchdog_verdict_carries_bad_steps_and_quarantine_counter():
    wd = Watchdog(HealthConfig(bad_steps=3, quarantine=True))
    losses = np.full(16, 1.0)
    skipped = np.zeros(16)
    skipped[5:8] = 1.0
    verdict = wd.observe_epoch(0, losses, skipped)
    assert verdict.rollback and verdict.bad_steps == [5, 6, 7]
    wd.note_quarantine(0, verdict.bad_steps, examples=96)
    assert wd.counters()["quarantined_examples"] == 96
    assert any(e["kind"] == "quarantine" for e in wd.events)


@pytest.mark.health
def test_trainer_quarantines_bad_window_on_rollback(tmp_path):
    """Host data mode + --health-quarantine: the nan_grad window's batch
    examples are quarantined at rollback, the replay excludes them, and
    the run still completes."""
    from distributed_training_comparison_tpu.train import Trainer
    from test_train import TinyNet

    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "128",
            "--batch-size", "32", "--epoch", "2",
            "--save-last-min-secs", "0", "--no-progress", "--seed", "7",
            "--data-mode", "host", "--workers", "0",
            "--ckpt-path", str(tmp_path),
            "--fault-plan", "nan_grad@epoch=1",
            "--health-quarantine", "--health-bad-steps", "3",
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    trainer.fit()
    counters = trainer.watchdog.counters()
    trainer.close()
    assert counters["rollbacks"] >= 1
    assert counters["quarantined_examples"] > 0
    quarantined = trainer.train_loader.quarantined
    assert len(quarantined) == counters["quarantined_examples"]
    events = obs.load_events(tmp_path / "version-0" / "events.jsonl")
    assert any(e["kind"] == "quarantine" for e in events)
    # the set SURVIVES a relaunch: the resume manifest carries it and the
    # fresh loader re-applies it — a corrupt shard must not re-enter the
    # stream just because the supervisor restarted the process
    resumed = Trainer(
        load_config(
            "tpu",
            argv=[
                "--synthetic-data", "--limit-examples", "128",
                "--batch-size", "32", "--epoch", "3",
                "--save-last-min-secs", "0", "--no-progress", "--seed", "7",
                "--data-mode", "host", "--workers", "0",
                "--ckpt-path", str(tmp_path), "--auto-resume",
                "--health-quarantine",
            ],
        ),
        model=TinyNet(num_classes=100),
    )
    try:
        assert resumed.train_loader.quarantined == quarantined
    finally:
        resumed.close()


# ------------------------------------------- partial desync fingerprints


def test_partial_fingerprints_matrix_and_injected_drift():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(model_parallel=2, backend="ddp")  # (4, 2)
    repl = jax.device_put(
        jnp.arange(12, dtype=jnp.float32).reshape(3, 4) - 5.0,
        NamedSharding(mesh, P()),
    )
    shard = jax.device_put(
        jnp.arange(16, dtype=jnp.float32).reshape(8, 2) + 1.0,
        NamedSharding(mesh, P("model", None)),
    )
    params = {"a": repl, "b": shard}
    matrix = partial_fingerprints(params, mesh)
    assert matrix.shape == (4, 2, 1)  # (data, model, pipe)
    # replicated across data: every model column is constant down axis 0
    assert (matrix.max(axis=0) == matrix.min(axis=0)).all()
    # the sharded leaf makes the two model columns DIFFER (each holds its
    # own half), which is exactly the per-shard visibility the scalar lacks
    assert matrix[0, 0, 0] != matrix[0, 1, 0]
    # absolute accounting: summing every device's partials recovers the
    # weighted checksums (leaf order: a -> weight 1, b -> weight 2).  The
    # replicated leaf appears once per device (8x1); the model-sharded
    # leaf's halves each appear once per data row (4x, weight 2 -> 8x).
    a_sum = float(np.abs(np.asarray(repl)).sum())
    b_sum = float(np.abs(np.asarray(shard)).sum())
    assert np.isclose(matrix.sum(), 8 * a_sum + 8 * b_sum)

    healthy = check_partial_desync(matrix)
    assert not healthy["mismatch"] and healthy["partial"] is True
    injected = check_partial_desync(matrix, inject=True)
    assert injected["mismatch"] and injected["spread"] > 0

    drifted = matrix.copy()
    drifted[2, 1, 0] += 0.5  # one replica's model-shard 1 drifted
    report = check_partial_desync(drifted)
    assert report["mismatch"]
    assert report["per_model_spread"][0] == 0.0
    assert report["per_model_spread"][1] == pytest.approx(0.5)


# ------------------------------------------------------ resize reporting


def _mk_fleet_run(root, run_id="cd" * 8):
    sup = EventBus(run_id=run_id)
    sup.emit("attempt_start", attempt=0, world_size=2, hosts=[0, 1])
    sup.emit(
        "attempt_end", attempt=0, returncode=75, preempted=True,
        world_size=2, hosts=[0, 1],
    )
    sup.emit(
        "resize", attempt=1, from_world=2, to_world=1,
        reason="host_lost", hosts=[0], lost=[1], returned=[],
    )
    sup.emit("attempt_start", attempt=1, world_size=1, hosts=[0])
    sup.emit(
        "attempt_end", attempt=1, returncode=0, preempted=False,
        world_size=1, hosts=[0],
    )
    root.mkdir(parents=True, exist_ok=True)
    with open(root / "events.jsonl", "w") as f:
        for ev in sup.ring_events():
            f.write(json.dumps(ev) + "\n")
    for attempt in (0, 1):
        bus = EventBus(run_id=run_id, attempt=attempt)
        bus.emit("run_start", epoch=0, world_size=2 - attempt)
        bus.emit("epoch_end", epoch=0, secs=1.0)
        bus.emit("goodput", step_s=4.0, wall_s=5.0)
        (root / "version-0").mkdir(exist_ok=True)
        with open(root / "version-0" / obs.events_filename(0), "a") as f:
            for ev in bus.ring_events():
                f.write(json.dumps(ev) + "\n")
    return root


def test_run_report_renders_resize_and_world_sizes(tmp_path):
    root = _mk_fleet_run(tmp_path / "run")
    events, _files = run_report.load_run(root)
    text = run_report.format_summary("fleet", run_report.summarize(events))
    assert "resize (attempt 1): world 2 -> 1 (host_lost; lost [1])" in text
    assert "world sizes:" in text and "a0=2" in text and "a1=1" in text
    assert run_report.main([str(root), "--check"]) == 0
    assert run_report.main(
        [str(root), "--check", "--require-kind", "resize"]
    ) == 0


def test_run_report_require_kind_resize_fails_without_one(tmp_path):
    root = tmp_path / "run"
    root.mkdir()
    bus = EventBus(run_id="ab" * 8)
    bus.emit("run_start", epoch=0)
    with open(root / "events.jsonl", "w") as f:
        for ev in bus.ring_events():
            f.write(json.dumps(ev) + "\n")
    assert run_report.main(
        [str(root), "--check", "--require-kind", "resize"]
    ) == 1


def test_goodput_aggregate_and_report_carry_resizes():
    resizes = [
        {"attempt": 1, "from_world": 2, "to_world": 1, "reason": "host_lost",
         "lost": [1], "returned": []},
        {"attempt": 2, "from_world": 1, "to_world": 2,
         "reason": "host_returned", "lost": [], "returned": [1]},
    ]
    report = aggregate_goodput(
        [{"step_s": 6.0, "wall_s": 8.0}], resizes=resizes,
    )
    assert report["resizes"] == resizes
    text = goodput_report.format_table([("fleet", report)])
    assert "resize a1 world 2 -> 1 (host_lost; lost [1])" in text
    assert "resize a2 world 1 -> 2 (host_returned; returned [1])" in text
    # reports without resizes render exactly as before
    plain = aggregate_goodput([{"step_s": 6.0, "wall_s": 8.0}])
    assert "resizes" not in plain


# ------------------------------------------------------------------- e2e


@pytest.mark.elastic
def test_e2e_fleet_kill_shrink_readmit_reexpand(tmp_path):
    """ISSUE 10 acceptance: a supervised 2-host fleet loses host 1 to a
    real SIGKILL mid-run -> the supervisor re-renders a world-size-1
    attempt that resumes from the verified checkpoint -> host 1 "returns"
    (fleet/host-1.up) -> a deliberate drain re-expands to 2 hosts -> the
    run completes with final params allclose to an uninterrupted run,
    ``resize`` events on the merged timeline, and ``run_report --check
    --require-kind resize`` green."""
    root = tmp_path / "run"
    goodput_json = tmp_path / "GOODPUT.json"
    cmd = [
        sys.executable, str(WORKER), "--supervise",
        "--fleet-hosts", "2", "--fleet-local-devices", "1",
        "--fleet-grace-secs", "3", "--fleet-poll-secs", "0.2",
        "--synthetic-data", "--limit-examples", "256",
        "--batch-size", "32", "--epoch", "10",
        "--no-progress", "--eval-step", "1000",
        "--save-last-min-secs", "0", "--seed", "7",
        "--device-chunk-steps", "2",
        "--heartbeat-secs", "0.2",
        "--ckpt-path", str(root),
        "--goodput-json", str(goodput_json),
        # insurance window: if the world-1 attempt races ahead of the
        # re-admission below, epoch 7 stalls 6s so the drain lands mid-run
        "--fault-plan", "stall@epoch=7:secs=6",
    ]
    proc = subprocess.Popen(
        cmd, cwd=WORKER.parent.parent,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    status = root / "fleet" / "status.json"
    events0 = root / "version-0" / "events.jsonl"

    def wait_for(cond, what, timeout=180.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                out, err = proc.communicate()
                raise AssertionError(
                    f"supervised fleet exited early waiting for {what}: "
                    f"rc={proc.returncode}\n{(err or '')[-3000:]}"
                )
            try:
                if cond():
                    return
            except (OSError, ValueError, KeyError):
                pass
            time.sleep(0.05)
        proc.kill()
        raise AssertionError(f"timed out waiting for {what}")

    def read_status():
        return json.loads(status.read_text())

    # phase 1: attempt 0 at world 2 has a verified checkpoint -> kill host 1
    wait_for(
        lambda: read_status()["attempt"] == 0
        and read_manifest(root / "version-0" / "last.ckpt") is not None,
        "attempt 0's first checkpoint",
    )
    os.kill(int(read_status()["pids"]["1"]), signal.SIGKILL)

    # phase 2: the re-rendered world-1 attempt is up and resumed -> host
    # 1 returns
    wait_for(
        lambda: read_status()["attempt"] == 1
        and any(
            '"kind": "run_start"' in line and '"attempt": 1' in line
            for line in events0.read_text().splitlines()
        ),
        "attempt 1's run_start",
    )
    (root / "fleet" / "host-1.up").write_text("")

    out, err = proc.communicate(timeout=420)
    assert proc.returncode == 0, (err or "")[-3000:]
    assert "Traceback" not in (err or ""), (err or "")[-3000:]

    events, _files = run_report.load_run(root)
    resizes = [
        e["payload"] for e in events if e["kind"] == "resize"
    ]
    assert [
        (r["from_world"], r["to_world"], r["reason"]) for r in resizes
    ] == [(2, 1, "host_lost"), (1, 2, "host_returned")], resizes
    starts = [
        e["payload"] for e in events
        if e["kind"] == "attempt_start" and e["payload"].get("world_size")
    ]
    assert [s["world_size"] for s in starts] == [2, 1, 2]
    # the shrunk attempt RESUMED (verified checkpoint), never retrained
    run_starts = {
        e["attempt"]: e["payload"] for e in events if e["kind"] == "run_start"
    }
    assert run_starts[1]["resumed"] is True
    assert run_starts[2]["resumed"] is True
    # the timeline is schema-clean and carries the required resize kind
    assert run_report.main([str(root), "--check"]) == 0
    assert run_report.main(
        [str(root), "--check", "--require-kind", "resize"]
    ) == 0
    # GOODPUT prices the shrink/expand
    gp = json.loads(goodput_json.read_text())
    assert len(gp["resizes"]) == 2 and gp["goodput_frac"] > 0

    # uninterrupted run, same seed, this process's 8-device mesh
    from distributed_training_comparison_tpu.train import Trainer
    from fleet_pool_worker import TinyNet

    clean_root = tmp_path / "clean"
    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "256",
            "--batch-size", "32", "--epoch", "10",
            "--no-progress", "--eval-step", "1000",
            "--save-last-min-secs", "0", "--seed", "7",
            "--device-chunk-steps", "2",
            "--ckpt-path", str(clean_root),
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    trainer.fit()
    trainer.close()

    def final_params(r):
        raw = serialization.msgpack_restore(
            (r / "version-0" / "last.ckpt").read_bytes()
        )
        assert raw["epoch"] == 9  # all 10 epochs completed
        return raw["state"]["params"]

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        final_params(root),
        final_params(clean_root),
    )
