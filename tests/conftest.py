"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference never tests its distributed path (SURVEY.md §4 — no tests at
all).  Here every SPMD code path runs in CI on 8 virtual CPU devices via
``--xla_force_host_platform_device_count``, the JAX-native analogue of
"test multi-GPU without GPUs".

Env note: on the axon TPU terminal, a sitecustomize registers the TPU
plugin at interpreter startup and pins ``jax_platforms`` — *before* pytest
imports this conftest — so setting ``JAX_PLATFORMS=cpu`` in os.environ here
is too late.  ``jax.config.update("jax_platforms", "cpu")`` after import
does work (the CPU backend is always registered), so that is the mechanism.
The XLA flag must still land before the CPU client is instantiated, hence
the module-scope environ write.
"""

import os
from pathlib import Path

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from distributed_training_comparison_tpu.utils import (  # noqa: E402
    enable_persistent_compilation_cache,
)

# Persistent executable cache: the fast gate (`pytest -m "not slow"`) is
# dominated by CPU compiles of the zoo models; with the cache warm a repeat
# run skips nearly all of them.
enable_persistent_compilation_cache()


@pytest.fixture(scope="session", autouse=True)
def _assert_virtual_mesh():
    devs = jax.devices()
    assert len(devs) == 8 and devs[0].platform == "cpu", (
        f"test suite must run on 8 virtual CPU devices, got {devs}"
    )


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture
def forced_device_env():
    """Factory for subprocess environments with a FORCED virtual CPU device
    count (``XLA_FLAGS=--xla_force_host_platform_device_count=N``).

    The elastic-restore tests need children running under *different*
    device counts than this process's 8-device mesh.  The flag must land
    before jax instantiates its CPU client, so it can only apply to fresh
    subprocesses — and it is passed via a per-child env COPY, never by
    mutating ``os.environ``, so nothing leaks into other tests (or into
    this process, whose backend is already up).
    """
    from distributed_training_comparison_tpu.resilience.elastic import (
        forced_host_device_env,
    )

    repo = Path(__file__).parent.parent

    def make(n: int) -> dict[str, str]:
        env = forced_host_device_env(n)
        env["PYTHONPATH"] = f"{repo}{os.pathsep}" + env.get("PYTHONPATH", "")
        return env

    return make
