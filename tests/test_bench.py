"""Bench-harness unit tests: the analytic FLOP model.

The throughput/MFU numbers the driver records are only as honest as this
formula; pin it against published reference points (torchvision MAC counts
× 2) so architecture edits that break the accounting fail loudly.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent.parent))

from bench import forward_flops_per_image, train_flops_per_image  # noqa: E402


def test_cifar_resnet18_flops():
    # 0.557 GMACs for CIFAR ResNet-18 at 32×32 → 1.11 GFLOPs forward
    assert forward_flops_per_image("resnet18") == pytest.approx(1.111e9, rel=0.01)


def test_imagenet_resnet50_flops_match_published():
    # torchvision resnet50 @224: 4.09 GMACs → 8.18 GFLOPs forward
    f = forward_flops_per_image("resnet50", 1000, 224, "imagenet")
    assert f == pytest.approx(8.18e9, rel=0.01)


def test_imagenet_resnet18_flops_match_published():
    # torchvision resnet18 @224: 1.81 GMACs → 3.63 GFLOPs forward
    f = forward_flops_per_image("resnet18", 1000, 224, "imagenet")
    assert f == pytest.approx(3.63e9, rel=0.01)


def test_train_is_three_forwards():
    assert train_flops_per_image("resnet50", 224, "imagenet") == pytest.approx(
        3 * forward_flops_per_image("resnet50", image_size=224, stem="imagenet"),
        rel=1e-9,
    )


def test_run_legs_retries_transient_failures(monkeypatch):
    """A leg that fails once (the remote-compile service dropping a
    connection) and succeeds on retry must record its numbers, not an
    error."""
    import bench

    calls = {"n": 0}

    def flaky_bench_native(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("response body closed before all bytes were read")
        return 2000.0

    monkeypatch.setattr(bench, "bench_native", flaky_bench_native)
    configs = [("leg", "resnet18", "bf16", 64, 32, "cifar", 128, 1, {})]
    per_config, _ = bench.run_legs(None, configs, 1, 197e12)
    assert per_config["leg"]["images_per_sec_per_chip"] == 2000.0
    assert calls["n"] == 2


def test_run_legs_isolates_leg_failures(monkeypatch):
    """One leg blowing up (the round-3 failure mode: a compile OOM) must
    record an error for that leg only — every other leg's numbers survive."""
    import bench

    def fake_bench_native(mesh, images, labels, model_name, *a, **kw):
        if model_name == "resnet50":
            raise RuntimeError("Mosaic scoped vmem OOM (simulated)")
        return 1000.0

    monkeypatch.setattr(bench, "bench_native", fake_bench_native)
    configs = [
        ("leg_ok", "resnet18", "bf16", 64, 32, "cifar", 128, 1, {}),
        ("leg_boom", "resnet50", "bf16", 64, 32, "cifar", 128, 1, {}),
        ("leg_vit", "vit_tiny", "bf16", 64, 32, "cifar", 128, 1, {}),
    ]
    per_config, data_cache = bench.run_legs(None, configs, 1, 197e12)
    assert per_config["leg_ok"]["images_per_sec_per_chip"] == 1000.0
    assert "vmem OOM" in per_config["leg_boom"]["error"]
    # tokens/s derived for transformer legs (64 tokens at 32px / patch 4)
    assert per_config["leg_vit"]["tokens_per_sec_per_chip"] == 64_000
    # the caller resolves the baseline leg's data from this cache by the
    # headline config's (n, image_size)
    assert (128, 32) in data_cache


def _full_record(n_legs: int = 12, n_flash: int = 10) -> dict:
    """A record shaped like a real full-size TPU run: every leg populated,
    long float values, one errored leg."""
    configs = {
        f"resnet50_bf16_bs128_224px_leg{i}": {
            "images_per_sec_per_chip": 34710.4,
            "train_flops_per_image": 24.524,
            "achieved_tflops": 123.67,
            "mfu": 0.6278,
            "tokens_per_sec_per_chip": 1529234,
        }
        for i in range(n_legs)
    }
    configs["leg_boom"] = {"error": "XlaRuntimeError: " + "x" * 480}
    return {
        "metric": "cifar100_resnet18_train_throughput",
        "value": 34710.4,
        "unit": "images/sec/chip",
        "vs_baseline": 20.878,
        "detail": {
            "platform": "tpu",
            "device_kind": "TPU v5 lite",
            "chips": 1,
            "chip_peak_bf16_tflops": 197.0,
            "headline_key": "resnet50_bf16_bs128_224px_leg0",
            "configs": configs,
            "flash_attention": {
                "head_dim": 128,
                "heads": 8,
                "configs": {
                    f"s{2 ** (11 + i // 2)}"
                    + ("_causal" if i % 2 else ""): {
                        "fwd_tflops": 105.7,
                        "fwd_bwd_tflops": 99.6,
                    }
                    for i in range(n_flash)
                },
                "reference_impl_tflops": 13.0,
                "speedup": 6.8,
            },
            "reference_style_images_per_sec": 1662.5,
            "baseline_definition": "same chip, reference loop shape",
        },
    }


def test_compact_line_fits_driver_budget():
    """The driver parses the final stdout JSON line out of a bounded tail
    capture; r4's full-detail line overflowed it (BENCH_r04 parsed=null).
    The compact line must stay within budget at full-run size AND survive
    a simulated tail capture."""
    import json

    import bench

    line = bench.compact_line(_full_record())
    assert len(line) <= 1500
    parsed = json.loads(line)
    assert parsed["metric"] == "cifar100_resnet18_train_throughput"
    assert parsed["value"] == 34710.4
    assert parsed["vs_baseline"] == 20.878
    # per-leg numbers survive compaction
    assert parsed["detail"]["ips"]["resnet50_bf16_bs128_224px_leg0"] == 34710.4
    assert parsed["detail"]["ips"]["leg_boom"] == "err"
    assert parsed["detail"]["flash_fwd_bwd_tflops"]["s2048"] == 99.6
    # simulate the driver: keep only the tail of a stdout stream whose
    # last line is the record, then parse the final line
    stream = "some earlier stdout noise\n" * 50 + line + "\n"
    tail = stream[-2000:]
    final_line = tail.strip().rsplit("\n", 1)[-1]
    assert json.loads(final_line) == parsed


def test_main_emits_one_budgeted_line_and_detail_file(monkeypatch, tmp_path, capsys):
    """bench.main() end-to-end with the measurement fns stubbed: stdout
    must be exactly ONE parseable JSON line within the driver budget, the
    full record must land in BENCH_DETAIL.json, and the baseline leg must
    replay the headline config's workload (batch/data resolved by
    headline_key, not list position — ADVICE r4)."""
    import json
    import os

    import bench

    seen = {}

    def fake_native(mesh, images, labels, model_name, precision, batch, *a, **kw):
        return 1000.0 * batch

    def fake_ref_style(mesh, images, labels, batch, steps):
        seen["baseline_batch"] = batch
        seen["baseline_n"] = len(images)
        return 500.0

    monkeypatch.setattr(bench, "bench_native", fake_native)
    monkeypatch.setattr(bench, "bench_reference_style", fake_ref_style)
    monkeypatch.setattr(
        bench, "bench_flash_attention", lambda *a, **kw: {"configs": {}}
    )
    monkeypatch.chdir(tmp_path)
    bench.main()
    out = capsys.readouterr().out.strip()
    assert "\n" not in out  # ONE line
    assert len(out) <= 1500
    parsed = json.loads(out)
    # cpu config: bs64 → fake 64k img/s over the 8-device CPU mesh
    assert parsed["value"] == 8_000.0
    assert parsed["vs_baseline"] == 128.0  # 8000 * 8 chips / 500
    assert seen["baseline_batch"] == 64
    full = json.load(open("BENCH_DETAIL.json"))
    assert full["value"] == parsed["value"]
    assert full["detail"]["headline_key"] == parsed["detail"]["headline_key"]
    assert set(parsed["detail"]["ips"]) == set(full["detail"]["configs"])


def test_compact_line_degrades_instead_of_overflowing():
    """Pathologically many legs: the compact line drops verbose sections
    (mfu first) rather than exceed the budget — headline fields are never
    sacrificed."""
    import json

    import bench

    line = bench.compact_line(_full_record(n_legs=40, n_flash=20))
    assert len(line) <= 1500
    parsed = json.loads(line)
    assert parsed["value"] == 34710.4
    assert "mfu" not in parsed["detail"]  # dropped to fit
