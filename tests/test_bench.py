"""Bench-harness unit tests: the analytic FLOP model.

The throughput/MFU numbers the driver records are only as honest as this
formula; pin it against published reference points (torchvision MAC counts
× 2) so architecture edits that break the accounting fail loudly.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent.parent))

from bench import forward_flops_per_image, train_flops_per_image  # noqa: E402


def test_cifar_resnet18_flops():
    # 0.557 GMACs for CIFAR ResNet-18 at 32×32 → 1.11 GFLOPs forward
    assert forward_flops_per_image("resnet18") == pytest.approx(1.111e9, rel=0.01)


def test_imagenet_resnet50_flops_match_published():
    # torchvision resnet50 @224: 4.09 GMACs → 8.18 GFLOPs forward
    f = forward_flops_per_image("resnet50", 1000, 224, "imagenet")
    assert f == pytest.approx(8.18e9, rel=0.01)


def test_imagenet_resnet18_flops_match_published():
    # torchvision resnet18 @224: 1.81 GMACs → 3.63 GFLOPs forward
    f = forward_flops_per_image("resnet18", 1000, 224, "imagenet")
    assert f == pytest.approx(3.63e9, rel=0.01)


def test_train_is_three_forwards():
    assert train_flops_per_image("resnet50", 224, "imagenet") == pytest.approx(
        3 * forward_flops_per_image("resnet50", image_size=224, stem="imagenet"),
        rel=1e-9,
    )


def test_run_legs_retries_transient_failures(monkeypatch):
    """A leg that fails once (the remote-compile service dropping a
    connection) and succeeds on retry must record its numbers, not an
    error."""
    import bench

    calls = {"n": 0}

    def flaky_bench_native(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("response body closed before all bytes were read")
        return 2000.0

    monkeypatch.setattr(bench, "bench_native", flaky_bench_native)
    configs = [("leg", "resnet18", "bf16", 64, 32, "cifar", 128, 1, {})]
    per_config, _ = bench.run_legs(None, configs, 1, 197e12)
    assert per_config["leg"]["images_per_sec_per_chip"] == 2000.0
    assert calls["n"] == 2


def test_run_legs_isolates_leg_failures(monkeypatch):
    """One leg blowing up (the round-3 failure mode: a compile OOM) must
    record an error for that leg only — every other leg's numbers survive."""
    import bench

    def fake_bench_native(mesh, images, labels, model_name, *a, **kw):
        if model_name == "resnet50":
            raise RuntimeError("Mosaic scoped vmem OOM (simulated)")
        return 1000.0

    monkeypatch.setattr(bench, "bench_native", fake_bench_native)
    configs = [
        ("leg_ok", "resnet18", "bf16", 64, 32, "cifar", 128, 1, {}),
        ("leg_boom", "resnet50", "bf16", 64, 32, "cifar", 128, 1, {}),
        ("leg_vit", "vit_tiny", "bf16", 64, 32, "cifar", 128, 1, {}),
    ]
    per_config, ref_data = bench.run_legs(None, configs, 1, 197e12)
    assert per_config["leg_ok"]["images_per_sec_per_chip"] == 1000.0
    assert "vmem OOM" in per_config["leg_boom"]["error"]
    # tokens/s derived for transformer legs (64 tokens at 32px / patch 4)
    assert per_config["leg_vit"]["tokens_per_sec_per_chip"] == 64_000
    assert ref_data is not None
