"""Bench-harness unit tests: the analytic FLOP model.

The throughput/MFU numbers the driver records are only as honest as this
formula; pin it against published reference points (torchvision MAC counts
× 2) so architecture edits that break the accounting fail loudly.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent.parent))

from bench import forward_flops_per_image, train_flops_per_image  # noqa: E402


def test_cifar_resnet18_flops():
    # 0.557 GMACs for CIFAR ResNet-18 at 32×32 → 1.11 GFLOPs forward
    assert forward_flops_per_image("resnet18") == pytest.approx(1.111e9, rel=0.01)


def test_imagenet_resnet50_flops_match_published():
    # torchvision resnet50 @224: 4.09 GMACs → 8.18 GFLOPs forward
    f = forward_flops_per_image("resnet50", 1000, 224, "imagenet")
    assert f == pytest.approx(8.18e9, rel=0.01)


def test_imagenet_resnet18_flops_match_published():
    # torchvision resnet18 @224: 1.81 GMACs → 3.63 GFLOPs forward
    f = forward_flops_per_image("resnet18", 1000, 224, "imagenet")
    assert f == pytest.approx(3.63e9, rel=0.01)


def test_train_is_three_forwards():
    assert train_flops_per_image("resnet50", 224, "imagenet") == pytest.approx(
        3 * forward_flops_per_image("resnet50", image_size=224, stem="imagenet"),
        rel=1e-9,
    )
