"""Auto-parallel planner tests (ISSUE 14, parallel/planner.py).

Covers: the cost-model fit from synthetic compile/dispatch events, the
feasibility filter against hand-constructed layouts (refusals carrying
``elastic.divisibility_help``-style numbers), plan == hand-flags
trajectory parity through the real Trainer, resize→replan under the
fleet supervisor (scripted FakeProc children — the real-subprocess
flavor lives in ``bench.py --plan``), the ``replan`` policy action
(act / dry-run / unavailable), the ``run_report --plan`` stream gate,
and the two satellite knobs (``--device-prefetch auto``,
``--ckpt-comms-residual``).
"""

import argparse
import json
import sys
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.parallel import planner
from distributed_training_comparison_tpu.parallel.planner import (
    Candidate,
    CostModel,
    PlanError,
    bubble_fraction,
    enumerate_candidates,
    fit_ledger,
    model_spec,
    plan_layout,
)

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
import run_report  # noqa: E402


def _hp(**kw):
    base = dict(
        model="vit_tiny", batch_size=128, grad_accum=1, grad_comms="fp32",
        pipeline_microbatches=0, num_devices=0, image_size=32, patch_size=0,
        parallel_plan="auto",
    )
    base.update(kw)
    return argparse.Namespace(**base)


# ------------------------------------------------------------ feasibility


def test_enumerate_respects_model_divisibility():
    spec = model_spec(_hp())  # vit_tiny: depth 12, heads 3
    cands, refusals = enumerate_candidates(8, spec, batch_size=128)
    keys = {c.key for c in cands}
    # heads=3 never divides by any tp that tiles 8 devices
    assert not any(c.model > 1 for c in cands)
    assert any("attention heads (3)" in r for r in refusals)
    # depth 12: pp2 (v1+v2) and pp4 (v1 only — 12 % 8 != 0) are legal
    assert "dp4xpp2" in keys and "dp4xpp2xv2" in keys
    assert "dp2xpp4" in keys and "dp2xpp4xv2" not in keys
    assert any("12 does not split into" in r for r in refusals)


def test_enumerate_batch_refusal_carries_legal_numbers():
    with pytest.raises(PlanError) as exc:
        plan_layout(_hp(batch_size=6), devices=4, device_kind="unknown")
    msg = str(exc.value)
    assert "legal data-parallel sizes" in msg
    assert "nearest legal batch sizes" in msg
    assert "no plan found" not in msg


def test_generic_model_plans_dp_only():
    plan = plan_layout(
        _hp(model="resnet18", batch_size=32), devices=4,
        device_kind="unknown",
    )
    assert all(c.model == 1 and c.pipe == 1 for c in plan.candidates)
    assert plan.chosen.key == "dp4"


def test_grad_comms_flag_is_the_numerics_ceiling():
    spec = model_spec(_hp())
    fp32, _ = enumerate_candidates(4, spec, batch_size=128)
    assert {c.grad_comms for c in fp32} == {"fp32"}
    int8, _ = enumerate_candidates(
        4, spec, batch_size=128, grad_comms_cap="int8"
    )
    assert {c.grad_comms for c in int8} == {"fp32", "fp16", "int8"}
    # nothing crosses the wire at dp=1: no compressed dp1 candidates
    assert not any(c.data == 1 and c.grad_comms != "fp32" for c in int8)


def test_moe_trunk_refuses_pipeline_allows_expert_parallel():
    spec = model_spec(_hp(model="vit_moe"))  # 8 experts, heads 3
    cands, refusals = enumerate_candidates(8, spec, batch_size=128)
    assert not any(c.pipe > 1 for c in cands)
    assert any("no stageable trunk" in r for r in refusals)
    # expert parallelism: 8 % tp == 0 → tp 2/4/8 legal
    assert {c.model for c in cands} == {1, 2, 4, 8}


# ------------------------------------------------------------- cost model


def _synthetic_ledger(points, *, k=4, devices=1, device_kind="TPU v4",
                      mesh=None, batch=128, hbm_limit=None):
    """Compile + metrics + run_start events for given (flops, secs/dispatch)
    points — the stream shape the real bus commits."""
    events = [
        {
            "kind": "run_start", "t_wall": 1.0, "process_index": 0,
            "attempt": 0,
            "payload": {"mesh": mesh or {"data": devices, "model": 1,
                                         "pipe": 1},
                        "batch_size": batch},
        }
    ]
    metrics = {}
    for i, (flops, secs) in enumerate(points):
        name = f"device_chunk_runner@k{k}" if i == 0 else f"exec{i}"
        fp = f"{i:016x}"
        events.append(
            {
                "kind": "compile", "t_wall": 2.0 + i, "process_index": 0,
                "attempt": 0,
                "payload": {
                    "name": name, "fingerprint": fp, "flops": flops,
                    "devices": devices, "device_kind": device_kind,
                    "argument_bytes": 1000.0, "temp_bytes": 500.0,
                    "peak_bytes": 1500.0,
                },
            }
        )
        metrics[f"exec/{name}:{fp[:8]}/dispatch_s"] = {
            "type": "histogram", "count": 10, "sum": secs * 10,
        }
    if hbm_limit is not None:
        metrics["res/hbm_limit_bytes"] = {"type": "gauge", "value": hbm_limit}
    events.append(
        {
            "kind": "metrics", "t_wall": 9.0, "process_index": 0,
            "attempt": 0, "payload": {"metrics": metrics},
        }
    )
    return events


def test_cost_model_fit_recovers_slope_and_intercept():
    a, b = 2e-12, 0.003
    flops = [1e9, 4e9, 8e9]
    events = _synthetic_ledger([(f, a * f + b) for f in flops])
    ledger = fit_ledger(events)
    assert len(ledger.points) == 3
    cm = CostModel.fit(ledger)
    assert cm.source == "ledger-fit" and cm.n_points == 3
    assert cm.secs_per_flop == pytest.approx(a, rel=1e-6)
    assert cm.overhead_s == pytest.approx(b, rel=1e-6)
    # device kind keyed the wire bandwidth off the planning table
    assert cm.wire_bytes_per_s == planner.WIRE_BYTES_PER_S_BY_DEVICE_KIND[
        "TPU v4"
    ]
    # the train exec's flops are whole-program per K-step dispatch
    assert ledger.step_flops_total == pytest.approx(1e9 / 4)
    assert ledger.measured_step_s == pytest.approx((a * 1e9 + b) / 4)


def test_cost_model_fallbacks():
    cm = CostModel.fit(None, device_kind="TPU v5p")
    assert cm.source == "peak-table"
    assert cm.secs_per_flop == pytest.approx(
        1.0 / (459e12 * planner.ASSUMED_MFU)
    )
    assert CostModel.fit(None, device_kind="weird").source == "default"


def test_fit_ledger_mesh_follows_the_chosen_executable_attempt():
    """A resized fleet's stream carries run_starts with DIFFERENT meshes;
    the footprint split must come from the attempt that compiled the
    chosen train executable, not whichever run_start came last — mixing
    them would mis-scale every candidate's predicted activation HBM."""
    events = _synthetic_ledger(
        [(8e9, 0.02)], mesh={"data": 4, "model": 1, "pipe": 1}, batch=128
    )
    # a later, shrunk attempt: new run_start (dp2) + a SMALLER train exec
    events.append(
        {
            "kind": "run_start", "t_wall": 20.0, "process_index": 0,
            "attempt": 1,
            "payload": {"mesh": {"data": 2, "model": 1, "pipe": 1},
                        "batch_size": 128},
        }
    )
    events.append(
        {
            "kind": "compile", "t_wall": 21.0, "process_index": 0,
            "attempt": 1,
            "payload": {
                "name": "device_chunk_runner@k4", "fingerprint": "f" * 16,
                "flops": 4e9, "devices": 2, "device_kind": "TPU v4",
                "temp_bytes": 900.0,
            },
        }
    )
    fit = fit_ledger(events)
    # attempt 0's exec has the larger flops -> ITS mesh (dp4) binds
    assert fit.captured_mesh == {"data": 4, "model": 1, "pipe": 1}
    assert fit.temp_bytes == 500.0


def test_ledger_at_different_batch_is_discarded():
    events = _synthetic_ledger([(1e9, 0.01)], batch=64)
    plan = plan_layout(
        _hp(batch_size=128), devices=4, device_kind="unknown", events=events
    )
    assert plan.ledger is None  # fell back to analytic flops
    assert plan.chosen.terms["flops_source"] == "analytic"


def test_predict_bubble_and_hbm_terms():
    assert bubble_fraction(1, 8) == 0.0
    assert bubble_fraction(2, 8, 1) == pytest.approx(2 / 10)
    assert bubble_fraction(2, 8, 2) == pytest.approx(4 / 20)
    spec = model_spec(_hp())
    cm = CostModel.fit(None, device_kind="unknown")
    plain = planner.predict(
        Candidate(data=2, model=1, pipe=1, devices=4), cm, spec,
        batch_size=128,
    )
    zero = planner.predict(
        Candidate(data=2, model=1, pipe=1, shard_optim=True, devices=4),
        cm, spec, batch_size=128,
    )
    # ZeRO halves the optimizer-state share of predicted HBM at dp=2
    assert zero.predicted_hbm_bytes < plain.predicted_hbm_bytes
    int8 = planner.predict(
        Candidate(data=2, model=1, pipe=1, grad_comms="int8", devices=4),
        cm, spec, batch_size=128,
    )
    # a compressed wire carries the params-shaped fp32 residual
    assert int8.predicted_hbm_bytes > plain.predicted_hbm_bytes
    assert int8.terms["sync_bytes"] == pytest.approx(
        plain.terms["sync_bytes"] / 4
    )
    piped = planner.predict(
        Candidate(data=2, model=1, pipe=2, microbatches=8, devices=4),
        cm, spec, batch_size=128,
    )
    assert piped.terms["bubble_frac"] == pytest.approx(0.2)
    assert piped.terms["compute_s"] > plain.terms["compute_s"]


def test_hbm_gate_refuses_with_numbers():
    # a limit so small every layout busts it → PlanError naming HBM
    events = _synthetic_ledger([(1e9, 0.01)], hbm_limit=1000.0)
    with pytest.raises(PlanError) as exc:
        plan_layout(
            _hp(), devices=4, device_kind="unknown", events=events
        )
    assert "predicted HBM" in str(exc.value)
    assert "device limit" in str(exc.value)


def test_plan_tie_break_prefers_simpler_layout():
    plan = plan_layout(_hp(batch_size=128), devices=4, device_kind="unknown")
    # dp4 and dp4xzero predict the same step seconds; the simpler wins
    assert plan.chosen.key == "dp4"
    assert not plan.chosen.shard_optim


def test_install_plan_writes_hparams():
    hp = _hp(model_parallel=1, pipeline_parallel=1, shard_optim=False,
             pipeline_schedule="gpipe", pipeline_virtual_stages=0,
             parallel_style="tensor")
    plan = plan_layout(hp, devices=4, device_kind="unknown")
    # force a pipeline winner to exercise every installed field
    plan.chosen = next(
        c for c in plan.candidates if c.pipe == 2 and c.virtual == 2
    )
    changed = planner.install_plan(plan, hp)
    assert hp.pipeline_parallel == 2
    assert hp.pipeline_schedule == "interleaved"
    assert hp.pipeline_virtual_stages == 2
    assert hp.pipeline_microbatches == plan.chosen.microbatches
    assert "pipeline_parallel" in changed


def test_plan_payload_is_bounded_and_complete():
    plan = plan_layout(
        _hp(grad_comms="int8"), devices=8, device_kind="unknown"
    )
    payload = plan.payload(installed=True, reason="construction")
    assert len(payload["candidates"]) <= planner.PLAN_EVENT_CANDIDATES
    assert payload["candidates_considered"] == len(plan.candidates)
    assert payload["fit"]["source"] in ("default", "peak-table", "ledger-fit")
    assert payload["layout"]["data"] == plan.chosen.data
    assert payload["flags"][:2] == ["--model-parallel", str(plan.chosen.model)]


# ----------------------------------------------------- staging depth (S2)


def test_auto_staging_depth():
    from distributed_training_comparison_tpu.parallel.planner import (
        auto_staging_depth,
    )

    assert auto_staging_depth(1e6, None, default=2) == 2  # no stats: default
    # 25% of 80MB headroom / 1MB chunks = 20 → capped at 8
    assert auto_staging_depth(1e6, 80_000_000) == 8
    assert auto_staging_depth(10e6, 80_000_000) == 2
    assert auto_staging_depth(1e9, 80_000_000) == 1  # never below 1


# -------------------------------------------------------- config flags


def test_config_parallel_plan_flags(tmp_path):
    hp = load_config("tpu", ["--parallel-plan", "auto",
                             "--ckpt-path", str(tmp_path)])
    assert hp.parallel_plan == "auto"
    hp = load_config("tpu", ["--device-prefetch", "auto",
                             "--ckpt-path", str(tmp_path)])
    assert hp.device_prefetch == "auto"
    hp = load_config("tpu", ["--device-prefetch", "3",
                             "--ckpt-path", str(tmp_path)])
    assert hp.device_prefetch == 3
    with pytest.raises(SystemExit):
        load_config("tpu", ["--device-prefetch", "bogus"])
    with pytest.raises(SystemExit):
        load_config("tpu", ["--parallel-plan", "bogus"])
    hp = load_config("tpu", ["--ckpt-comms-residual",
                             "--ckpt-path", str(tmp_path)])
    assert hp.ckpt_comms_residual is True
    assert load_config("tpu", []).ckpt_comms_residual is False


# ------------------------------------------------- run_report --plan gate


def _write_events(path: Path, events) -> Path:
    path.write_text("".join(json.dumps(e) + "\n" for e in events))
    return path


def _plan_event(layout, *, installed=True, attempt=0, world=None,
                t_wall=10.0):
    payload = {
        "chosen": {"key": "k", **layout},
        "layout": layout,
        "installed": installed,
        "reason": "construction",
        "devices": 4,
        "batch_size": 32,
        "candidates": [
            {"key": "k", "predicted_step_s": 0.01,
             "predicted_hbm_bytes": 1e6, **layout}
        ],
        "fit": {"source": "default"},
        "attempt": attempt,
    }
    if world is not None:
        payload["world"] = world
    return {
        "kind": "plan", "t_wall": t_wall, "process_index": 0,
        "attempt": attempt, "payload": payload,
    }


def _run_start_event(mesh, *, attempt=0, world_size=1, t_wall=11.0,
                     shard_optim=False, grad_comms="fp32"):
    return {
        "kind": "run_start", "t_wall": t_wall, "process_index": 0,
        "attempt": attempt,
        "payload": {
            "mesh": mesh, "world_size": world_size, "batch_size": 32,
            "shard_optim": shard_optim, "grad_comms": grad_comms,
        },
    }


LAYOUT_DP4 = {"data": 4, "model": 1, "pipe": 1, "shard_optim": False,
              "grad_comms": "fp32"}


def test_plan_report_green_on_agreement(tmp_path, capsys):
    _write_events(
        tmp_path / "events.jsonl",
        [
            _plan_event(LAYOUT_DP4),
            _run_start_event({"data": 4, "model": 1, "pipe": 1}),
        ],
    )
    assert run_report.plan_report(tmp_path) == 0
    out = capsys.readouterr().out
    assert "matches its attempt's run_start layout" in out


def test_plan_report_fails_on_silently_ignored_plan(tmp_path, capsys):
    _write_events(
        tmp_path / "events.jsonl",
        [
            _plan_event(LAYOUT_DP4),
            _run_start_event({"data": 2, "model": 2, "pipe": 1}),
        ],
    )
    assert run_report.plan_report(tmp_path) == 1
    assert "PLAN MISMATCH" in capsys.readouterr().out


def test_plan_report_dump_mode_never_gates(tmp_path):
    _write_events(
        tmp_path / "events.jsonl",
        [
            _plan_event(LAYOUT_DP4, installed=False),
            _run_start_event({"data": 2, "model": 2, "pipe": 1}),
        ],
    )
    assert run_report.plan_report(tmp_path) == 0


def test_plan_report_scales_data_axis_by_world_share(tmp_path):
    # the pid-level CPU fleet emulation: the plan sized 4 data shards for
    # 2 hosts, rank 0 joined a 1-host world and ran data=2 — consistent
    _write_events(
        tmp_path / "events.jsonl",
        [
            _plan_event(LAYOUT_DP4, world=2),
            _run_start_event({"data": 2, "model": 1, "pipe": 1},
                             world_size=1),
        ],
    )
    assert run_report.plan_report(tmp_path) == 0
    # but a model-axis disagreement still fails whatever the worlds
    _write_events(
        tmp_path / "events.jsonl",
        [
            _plan_event(
                {**LAYOUT_DP4, "model": 2}, world=2,
            ),
            _run_start_event({"data": 2, "model": 1, "pipe": 1},
                             world_size=1),
        ],
    )
    assert run_report.plan_report(tmp_path) == 1


def test_plan_report_no_events_and_no_plans(tmp_path):
    assert run_report.plan_report(tmp_path / "missing") == 2
    _write_events(
        tmp_path / "events.jsonl",
        [_run_start_event({"data": 4, "model": 1, "pipe": 1})],
    )
    assert run_report.plan_report(tmp_path) == 0


# -------------------------------------------------- replan policy action


class _Bus:
    def __init__(self):
        self.events = []

    def emit(self, kind, **payload):
        ev = {"kind": kind, "payload": payload, "t_wall": time.time()}
        self.events.append(ev)
        return ev


def _alert(metric="compile/peak_hbm_bytes", spec=None):
    return {
        "kind": "alert", "t_wall": time.time() + 60.0,
        "payload": {
            "state": "firing", "metric": metric,
            "spec": spec or f"{metric}:value>1", "source": "p0", "value": 2,
        },
    }


def test_replan_action_acts_and_dry_runs():
    from distributed_training_comparison_tpu.ops import policy as policy_mod

    calls = []
    for mode, expect_called in (("act", True), ("dry-run", False)):
        bus = _Bus()
        engine = policy_mod.PolicyEngine(
            [policy_mod.PolicyRule.parse(
                "compile/peak_hbm_bytes:value>1 -> replan:cooldown=0"
            )],
            bus=bus, mode=mode,
        )
        engine.bind(
            "replan",
            lambda d: calls.append(d) or {"reason": "test"},
        )
        engine.observe_event(_alert())
        states = [e["payload"]["state"] for e in bus.events]
        if expect_called:
            assert states == ["requested", "completed"]
            assert calls and calls[-1]["action"] == "replan"
        else:
            assert states == ["dry_run"]
            assert not calls
        calls.clear()


def test_replan_unavailable_reports_failed():
    from distributed_training_comparison_tpu.ops import policy as policy_mod

    bus = _Bus()
    engine = policy_mod.PolicyEngine(
        [policy_mod.PolicyRule.parse(
            "compile/peak_hbm_bytes:value>1 -> replan"
        )],
        bus=bus, mode="act",
    )
    # supervisor_actions with no planner: the executor raises → 'failed'
    actions = policy_mod.supervisor_actions("/nonexistent", fleet_hosts=2)
    engine.bind("replan", actions["replan"])
    engine.observe_event(_alert())
    states = [e["payload"]["state"] for e in bus.events]
    assert states == ["requested", "failed"]
    assert "--parallel-plan auto" in bus.events[-1]["payload"]["error"]


def test_replan_rule_validates_at_cli(tmp_path):
    hp = load_config(
        "tpu",
        ["--alert", "compile/peak_hbm_bytes:value>1e9",
         "--policy", "compile/peak_hbm_bytes -> replan:cooldown=30",
         "--ckpt-path", str(tmp_path)],
    )
    assert hp.policy
    with pytest.raises(SystemExit):
        load_config(
            "tpu", ["--policy", "compile/peak_hbm_bytes -> replan"]
        )  # trigger names no alert rule


# ------------------------------------- fleet: resize → replan (scripted)


from distributed_training_comparison_tpu.resilience.fleet import (  # noqa: E402
    FleetSupervisor,
)
from distributed_training_comparison_tpu.resilience.preempt import (  # noqa: E402
    EXIT_PREEMPTED,
)


class FakeProc:
    _next_pid = 7000

    def __init__(self, rc, runs_for=3):
        FakeProc._next_pid += 1
        self.pid = FakeProc._next_pid
        self._rc_final = rc
        self._runs_for = runs_for
        self._polls = 0
        self._rc = None
        self._terminated = False

    def poll(self):
        self._polls += 1
        if self._rc is None:
            if self._terminated:
                self._rc = EXIT_PREEMPTED
            elif self._rc_final is not None and self._polls > self._runs_for:
                self._rc = self._rc_final
        return self._rc

    def terminate(self):
        self._terminated = True

    def kill(self):
        self._rc = -9


def _plan_fleet(tmp_path, scripts, events, **kw):
    it = iter(scripts)
    spawned = []

    def spawn(cmd, env):
        rc, runs_for = next(it)
        p = FakeProc(rc, runs_for)
        p.cmd = list(cmd)
        spawned.append(p)
        return p

    kw.setdefault("hosts", 2)
    kw.setdefault("batch_size", 32)
    kw.setdefault("local_devices", 2)
    kw.setdefault("grace_s", 0.0)
    kw.setdefault("poll_s", 0.05)
    kw.setdefault(
        "plan_hparams",
        _hp(model="resnet18", batch_size=32, parallel_plan="auto"),
    )
    sup = FleetSupervisor(
        ["train.py", "--epoch", "3", "--model-parallel", "1",
         "--parallel-plan", "auto"],
        ckpt_root=tmp_path,
        spawn=spawn,
        sleep=lambda s: None,
        log=lambda m: None,
        events=lambda kind, **p: events.append((kind, p)),
        **kw,
    )
    return sup, spawned


def test_fleet_resize_triggers_replan_with_different_layout(tmp_path):
    events: list = []
    # attempt 0 (world 2, 4 devices): host 1 dies by external SIGKILL;
    # attempt 1 (world 1, 2 devices) completes clean
    scripts = [(None, 0), (-9, 1), (0, 2)]
    sup, spawned = _plan_fleet(tmp_path, scripts, events)
    summary = sup.run()
    assert summary["final_rc"] == 0
    kinds = [k for k, _ in events]
    plans = [p for k, p in events if k == "plan"]
    assert len(plans) == 2
    assert [p["reason"] for p in plans] == ["attempt_plan", "resize"]
    # the shrunk fleet re-planned onto a DIFFERENT legal layout: the
    # resize event precedes the new plan, whose data axis halved
    assert kinds.index("resize") < len(kinds) - 1 - kinds[::-1].index("plan")
    assert plans[0]["layout"]["data"] == 4
    assert plans[1]["layout"]["data"] == 2
    assert plans[0]["world"] == 2 and plans[1]["world"] == 1
    assert all(p["installed"] for p in plans)
    # the rendered child argv carries the plan's flags and disables the
    # child-side planner; the caller's own layout flags are stripped
    cmd = spawned[-1].cmd
    assert cmd[cmd.index("--parallel-plan") + 1] == "off"
    assert cmd.count("--parallel-plan") == 1
    assert cmd[cmd.index("--model-parallel") + 1] == "1"
    assert cmd.count("--model-parallel") == 1
    assert "--no-shard-optim" in cmd
    # the compact plan ledger rides the summary (GOODPUT's supervisor)
    assert [p["world"] for p in summary["plans"]] == [2, 1]


def test_policy_replan_drains_and_replans_budget_free(tmp_path):
    events: list = []
    # attempt 0: both ranks healthy until the replan drain; attempt 1 ok
    scripts = [(None, 0), (None, 0), (0, 2), (0, 2)]
    sup, spawned = _plan_fleet(tmp_path, scripts, events, max_restarts=0)
    orig = sup._launch

    def launch(attempt):
        if attempt == 0:
            sup.request_replan("hbm breach (test)")
        return orig(attempt)

    sup._launch = launch
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert "give_up" not in [k for k, _ in events]  # drain was budget-free
    plans = [p for k, p in events if k == "plan"]
    assert [p["reason"] for p in plans] == ["attempt_plan", "policy_replan"]
    assert plans[1]["replan_trigger"] == "hbm breach (test)"
    assert summary["planned_drains"] == 1


def test_fleet_without_plan_hparams_keeps_legacy_selection(tmp_path):
    events: list = []
    scripts = [(0, 2), (0, 2)]
    sup, spawned = _plan_fleet(
        tmp_path, scripts, events, plan_hparams=None
    )
    assert sup.plan_hparams is None
    with pytest.raises(ValueError):
        sup.request_replan("nope")
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert "plan" not in [k for k, _ in events]
    assert "plans" not in summary
    # caller flags survive un-stripped when no plan owns the layout
    assert "--parallel-plan" in spawned[-1].cmd


def test_plan_world_respects_host_batch_divisibility(tmp_path):
    """A per-device-legal candidate can still crash every child: rank
    construction hard-enforces batch % processes == 0
    (host_local_batch_slice).  vit_tiny on 3 hosts x 2 devices admits
    dp2xtp3 (batch 32 % dp 2 == 0) — but 32 % 3 hosts != 0, so world 3
    must be refused and the plan land on world 2."""
    events: list = []
    sup, _ = _plan_fleet(
        tmp_path, [(0, 2)] * 6, events, hosts=3,
        plan_hparams=_hp(model="vit_tiny", batch_size=32,
                         parallel_plan="auto"),
    )
    world, plan, errors = sup._plan_world(3)
    assert world == 2
    assert any("not divisible by 3 host(s)" in e for e in errors)
    assert plan.chosen.data * plan.chosen.model * plan.chosen.pipe == 4


def test_fleet_fallback_to_widest_legal_disables_child_planner(tmp_path):
    """Every world's plan refused (generic model, batch 6 never divides
    the dp-only device counts) but the caller's hand --model-parallel 2
    mesh IS legal at full width: the attempt falls back to the classic
    widest-legal selection, keeps the caller's layout flags, and the
    children get --parallel-plan off — a child-side re-plan would
    re-raise the same refusal at construction and burn the budget."""
    events: list = []
    scripts = [(0, 2)] * 3
    it = iter(scripts)
    spawned = []

    def spawn(cmd, env):
        rc, runs_for = next(it)
        p = FakeProc(rc, runs_for)
        p.cmd = list(cmd)
        spawned.append(p)
        return p

    sup = FleetSupervisor(
        ["train.py", "--model-parallel", "2", "--parallel-plan", "auto"],
        ckpt_root=tmp_path, spawn=spawn, sleep=lambda s: None,
        log=lambda m: None,
        events=lambda kind, **p: events.append((kind, p)),
        hosts=3, batch_size=6, local_devices=4, model_parallel=2,
        grace_s=0.0, poll_s=0.05,
        plan_hparams=_hp(model="resnet18", batch_size=6,
                         parallel_plan="auto"),
    )
    summary = sup.run()
    assert summary["final_rc"] == 0
    assert "plan" not in [k for k, _ in events]  # nothing plannable
    cmd = spawned[-1].cmd
    assert cmd[cmd.index("--parallel-plan") + 1] == "off"
    assert cmd.count("--parallel-plan") == 1
    # the caller's hand layout survived un-stripped
    assert cmd[cmd.index("--model-parallel") + 1] == "2"


def test_fleet_plan_refusal_names_numbers(tmp_path):
    events: list = []
    # batch 30 on 2×2 devices: no dp in {1,2,4} divides 30 evenly at
    # width 4... (30 % 4 != 0, 30 % 2 == 0) — force total refusal with
    # min_hosts=2 so the legal 1-host world is below the floor
    sup, _ = _plan_fleet(
        tmp_path, [(0, 2)], events,
        batch_size=30, min_hosts=2,
        plan_hparams=_hp(model="resnet18", batch_size=30,
                         parallel_plan="auto"),
    )
    from distributed_training_comparison_tpu.resilience.fleet import (
        FleetPlanError,
    )

    with pytest.raises(FleetPlanError) as exc:
        sup.run()
    assert "30" in str(exc.value)


# ------------------------------------ trainer e2e: plan == hand flags


def _trainer_hp(tmp_path, *extra):
    return load_config(
        "tpu",
        [
            "--synthetic-data", "--limit-examples", "96",
            "--batch-size", "16", "--epoch", "1",
            "--no-progress", "--eval-step", "10000",
            "--save-last-min-secs", "0", "--seed", "7",
            "--num-devices", "4",
            "--ckpt-path", str(tmp_path),
            *extra,
        ],
    )


def _fit_losses(hp):
    from distributed_training_comparison_tpu.models.vit import ViT
    from distributed_training_comparison_tpu.train import Trainer

    trainer = Trainer(hp, model=ViT(depth=2, dim=32, heads=2))
    try:
        trainer.fit()
    finally:
        trainer.close()
    events = planner.load_ledger_events(hp.ckpt_path)
    losses = [
        e["payload"]["train_loss"]
        for e in events
        if e.get("kind") == "epoch_end"
    ]
    return trainer, losses, events


@pytest.mark.slow
def test_trainer_plan_matches_hand_flags_trajectory(tmp_path):
    """--parallel-plan auto must install a layout whose trajectory is the
    one the same flags hand-picked produce — and the plan event must agree
    with run_start (run_report --plan green)."""
    planned, p_losses, p_events = _fit_losses(
        _trainer_hp(tmp_path / "plan", "--parallel-plan", "auto")
    )
    assert planned.plan is not None and planned._plan_installed
    plan_evs = [e for e in p_events if e.get("kind") == "plan"]
    assert len(plan_evs) == 1
    chosen = plan_evs[0]["payload"]["chosen"]
    hand, h_losses, _ = _fit_losses(
        _trainer_hp(
            tmp_path / "hand",
            *plan_evs[0]["payload"]["flags"],
        )
    )
    assert dict(hand.mesh.shape) == dict(planned.mesh.shape)
    np.testing.assert_allclose(p_losses, h_losses, rtol=0, atol=0)
    # the stream gate: installed plan == run_start layout
    assert run_report.plan_report(tmp_path / "plan") == 0
    rs = [e for e in p_events if e.get("kind") == "run_start"][0]["payload"]
    assert rs["mesh"]["data"] == chosen["data"]
    assert rs["mesh"]["model"] == chosen["model"]


def test_trainer_dump_mode_survives_plan_refusal(tmp_path, monkeypatch):
    """dump 'scores and logs, never gates': a PlanError must not kill a
    run whose hand flags are legal — auto, with nothing to install,
    still raises the refusal."""
    from distributed_training_comparison_tpu.models.vit import ViT
    from distributed_training_comparison_tpu.train import Trainer

    def refuse(*a, **k):
        raise PlanError("no feasible layout (test)")

    monkeypatch.setattr(planner, "plan_layout", refuse)
    hp = _trainer_hp(tmp_path, "--parallel-plan", "dump")
    trainer = Trainer(hp, model=ViT(depth=2, dim=32, heads=2))
    try:
        assert trainer.plan is None
        assert trainer._plan_refusal == "no feasible layout (test)"
        assert dict(trainer.mesh.shape) == {"data": 4, "model": 1, "pipe": 1}
    finally:
        trainer.close()
    with pytest.raises(PlanError):
        Trainer(
            _trainer_hp(tmp_path / "auto", "--parallel-plan", "auto"),
            model=ViT(depth=2, dim=32, heads=2),
        )


@pytest.mark.slow
def test_trainer_dump_mode_keeps_hand_flags(tmp_path):
    hp = _trainer_hp(tmp_path, "--parallel-plan", "dump")
    from distributed_training_comparison_tpu.models.vit import ViT
    from distributed_training_comparison_tpu.train import Trainer

    trainer = Trainer(hp, model=ViT(depth=2, dim=32, heads=2))
    try:
        assert trainer.plan is not None
        assert not trainer._plan_installed
        # hand flags kept: the default layout, whatever the plan said
        assert dict(trainer.mesh.shape) == {"data": 4, "model": 1, "pipe": 1}
        trainer.bus.emit("run_end", epoch=0)
    finally:
        trainer.close()
    # a dump-mode plan never gates the stream
    assert run_report.plan_report(tmp_path) == 0


# -------------------------------------- comms residual checkpointing (S1)


def _residual_hp(tmp_path, *extra):
    return load_config(
        "tpu",
        [
            "--synthetic-data", "--limit-examples", "96",
            "--batch-size", "16", "--epoch", "2",
            "--no-progress", "--eval-step", "10000",
            "--save-last-min-secs", "0", "--seed", "7",
            "--num-devices", "2",
            "--grad-comms", "int8",
            "--ckpt-path", str(tmp_path),
            *extra,
        ],
    )


def _tiny_model():
    import flax.linen as lnn
    import jax.numpy as jnp

    class TinyNet(lnn.Module):
        num_classes: int = 100

        @lnn.compact
        def __call__(self, x, train: bool = False):
            x = lnn.Conv(8, (3, 3), strides=2, use_bias=False)(x)
            x = lnn.BatchNorm(use_running_average=not train)(x)
            x = lnn.relu(x)
            x = jnp.mean(x, axis=(1, 2))
            return lnn.Dense(self.num_classes)(x)

    return TinyNet()


@pytest.mark.slow
def test_ckpt_comms_residual_roundtrip_and_cross_flag_drop(tmp_path):
    from distributed_training_comparison_tpu.resilience import read_manifest
    from distributed_training_comparison_tpu.train import Trainer

    hp = _residual_hp(tmp_path, "--ckpt-comms-residual")
    trainer = Trainer(hp, model=_tiny_model())
    try:
        trainer.fit()
    finally:
        trainer.close()
    last = Path(trainer.version_dir) / "last.ckpt"
    manifest = read_manifest(last)
    assert manifest["comms_residual"] is True
    # the serialized payload genuinely carries the residual leaves
    from flax import serialization

    raw = serialization.msgpack_restore(last.read_bytes())
    assert "comms_residual" in raw["state"]
    res_leaves = raw["state"]["comms_residual"]
    total = float(
        sum(
            np.abs(np.asarray(l)).sum()
            for l in jax.tree_util.tree_leaves(res_leaves)
        )
    )
    assert total > 0.0  # int8 EF residual after 2 epochs is nonzero

    # same-flag resume restores it (not zeros)
    hp2 = _residual_hp(tmp_path, "--ckpt-comms-residual",
                       "--resume", str(last), "--epoch", "3")
    t2 = Trainer(hp2, model=_tiny_model())
    try:
        restored = float(
            sum(
                np.abs(np.asarray(l)).sum()
                for l in jax.tree_util.tree_leaves(t2.state.comms_residual)
            )
        )
        assert restored == pytest.approx(total, rel=1e-6)
    finally:
        t2.close()

    # cross-flag restore, SAME wire: the restoring run kept --grad-comms
    # int8 but dropped --ckpt-comms-residual — flag-off behavior wins
    # (drop and warn, residual restarts at zero), never a silent restore
    # off an absent flag
    hp2b = _residual_hp(tmp_path, "--resume", str(last), "--epoch", "3")
    t2b = Trainer(hp2b, model=_tiny_model())
    try:
        assert t2b.state.comms_residual is not None  # int8 wire carries one
        dropped = float(
            sum(
                np.abs(np.asarray(l)).sum()
                for l in jax.tree_util.tree_leaves(t2b.state.comms_residual)
            )
        )
        assert dropped == 0.0
    finally:
        t2b.close()

    # cross-flag restore (fp32 wire now): documented drop-and-warn path —
    # the run constructs fine and carries NO residual
    hp3 = load_config(
        "tpu",
        [
            "--synthetic-data", "--limit-examples", "96",
            "--batch-size", "16", "--epoch", "3",
            "--no-progress", "--eval-step", "10000",
            "--save-last-min-secs", "0", "--seed", "7",
            "--num-devices", "2",
            "--ckpt-path", str(tmp_path),
            "--resume", str(last),
        ],
    )
    t3 = Trainer(hp3, model=_tiny_model())
    try:
        assert t3.state.comms_residual is None
    finally:
        t3.close()


def test_ckpt_without_residual_resumes_with_zeros(tmp_path):
    """Flag-off checkpoints keep the old shape; a comms run resuming one
    restarts the residual at zero (the pre-satellite contract)."""
    import jax as _jax
    import jax.numpy as jnp
    from distributed_training_comparison_tpu.parallel import make_mesh
    from distributed_training_comparison_tpu.train import checkpoint as ckpt
    from distributed_training_comparison_tpu.train.state import TrainState
    import optax

    tx = optax.sgd(0.1)
    params = {"w": jnp.ones((4, 4))}
    base = TrainState(
        step=jnp.zeros((), jnp.int32), params=params, batch_stats={},
        opt_state=tx.init(params), apply_fn=lambda *a, **k: None, tx=tx,
    )
    vdir = tmp_path
    ckpt.save_resume_state(vdir, base, 0, 0.5)
    raw = (vdir / "last.ckpt").read_bytes()
    from flax import serialization

    assert "comms_residual" not in serialization.msgpack_restore(raw)["state"]
    # restoring WITH a residual-carrying state injects zeros, not a crash
    carrying = base.replace(
        comms_residual={"w": jnp.full((4, 4), 7.0)}
    )
    info: dict = {}
    restored, next_epoch, best = ckpt.load_resume_state(
        vdir / "last.ckpt", carrying, info=info
    )
    assert info["comms_residual"] == "absent"
    assert next_epoch == 1 and best == 0.5
    # saving the carrying state DOES serialize the residual, and a
    # wire-layout change on restore drops it
    ckpt.save_resume_state(vdir, carrying, 1, 0.6)
    mismatched = base.replace(
        comms_residual={"w": jnp.zeros((2, 2))}
    )
    info2: dict = {}
    ckpt.load_resume_state(vdir / "last.ckpt", mismatched, info=info2)
    assert info2["comms_residual"] == "dropped:wire-layout-changed"
    info3: dict = {}
    ok, _, _ = ckpt.load_resume_state(
        vdir / "last.ckpt", carrying, info=info3
    )
    assert info3["comms_residual"] == "restored"
    np.testing.assert_array_equal(
        np.asarray(ok.comms_residual["w"]), np.full((4, 4), 7.0)
    )


import jax  # noqa: E402  (used by the residual e2e above)
