"""Multi-host worker: one JAX process of a 2-process CPU 'cluster'.

Launched by tests/test_multihost.py with
``XLA_FLAGS=--xla_force_host_platform_device_count=4`` so the pair forms an
8-device, 2-process mesh — the CI analogue of two TPU hosts over DCN.  This
executes the ``jax.process_count() > 1`` branches that single-process tests
can never reach (the reference never tests multi-node at all, SURVEY.md §4):

- ``parallel.init_distributed`` → ``jax.distributed.initialize`` rendezvous
  (the ``dist.init_process_group`` analogue, ``src/ddp/main.py:18-23``),
- ``place_tree``/``put_replicated`` global assembly from per-process hosts,
- ``shard_batch`` per-process contribution to a global batch,
- one SPMD train step whose gradient all-reduce crosses 'hosts',
- the ``test()``-style best-checkpoint broadcast: process-0 value →
  ``broadcast_one_to_all`` → re-place.

Prints one ``RESULT`` line the parent asserts on (loss equality across
processes proves the collective actually synchronized them).
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU plugin

import flax.linen as nn  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


class TinyNet(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(8, (3, 3), strides=2, use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def main(rank: int, port: int) -> None:
    from distributed_training_comparison_tpu import parallel
    from distributed_training_comparison_tpu.train import (
        configure_optimizers,
        create_train_state,
        make_train_step,
    )

    class HP:
        world_size = 2
        dist_url = f"127.0.0.1:{port}"
        lr = 0.05
        weight_decay = 1e-4
        lr_decay_step_size = 25
        lr_decay_gamma = 0.1

    HP.rank = rank
    parallel.init_distributed(HP)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.process_index() == rank
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    mesh = parallel.make_mesh(backend="ddp")
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(TinyNet(), jax.random.key(0), tx)
    sharding = parallel.state_shardings(mesh, state)
    state = parallel.place_tree(state, sharding)  # multi-host assembly branch

    # per-process half of a global batch of 32 — both processes build the
    # same global data, each contributes its slice (DistributedSampler
    # analogue; see parallel/sharding.py shard_batch)
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(32, 32, 32, 3)).astype(np.float32)
    gy = rng.integers(0, 10, size=(32,)).astype(np.int32)
    half = 16
    lx, ly = gx[rank * half : (rank + 1) * half], gy[rank * half : (rank + 1) * half]
    bx, by = parallel.shard_batch((lx, ly), mesh)
    assert bx.shape == (32, 32, 32, 3), bx.shape  # global shape, not local

    step = make_train_step(mesh, augment=False, state_sharding=sharding)
    state, metrics = step(state, bx, by, jax.random.key(1))
    loss = float(metrics["loss"])  # replicated global scalar

    # fetch_to_host: multi-host replicated leaves take the collective-free
    # local-read path (safe from one process alone); partitioned leaves take
    # the symmetric all-gather path — both must return the global value
    from distributed_training_comparison_tpu.parallel.sharding import (
        fetch_to_host,
        needs_collective_fetch,
    )

    host_params = fetch_to_host(state.params)  # replicated → local read
    for leaf in jax.tree_util.tree_leaves(host_params):
        assert isinstance(leaf, np.ndarray)
    gvals = np.arange(32, dtype=np.float32)
    sharded = parallel.shard_batch(gvals.reshape(2, 16)[rank], mesh)
    assert needs_collective_fetch(sharded) and not needs_collective_fetch(
        host_params
    )
    gathered = fetch_to_host(sharded)  # partitioned → all-gather, symmetric
    assert np.array_equal(gathered, gvals), gathered

    # chunked host-streaming layout (K, B, ...) assembles across processes
    # with the batch on axis 1 (shard_batch(batch_axis=1) multi-host branch)
    gchunk = np.arange(2 * 32, dtype=np.float32).reshape(2, 32)
    local_chunk = gchunk[:, rank * 16 : (rank + 1) * 16]
    chunk_arr = parallel.shard_batch(local_chunk, mesh, batch_axis=1)
    assert chunk_arr.shape == (2, 32), chunk_arr.shape
    assert np.array_equal(fetch_to_host(chunk_arr), gchunk)

    # the test() broadcast pattern (train/trainer.py): process-0's params win
    from jax.experimental import multihost_utils

    local_params = jax.device_get(state.params)
    if rank != 0:
        local_params = jax.tree_util.tree_map(lambda a: a * 0.0, local_params)
    synced = multihost_utils.broadcast_one_to_all(local_params)
    placed = parallel.place_tree(synced, sharding.params)
    # broadcast restored process-0's (trained, nonzero) values everywhere
    l2 = sum(
        float(jnp.sum(jnp.abs(x))) for x in jax.tree_util.tree_leaves(placed)
    )
    assert l2 > 0.0, "broadcast lost process-0 params"

    print(
        f"RESULT rank={rank} procs={jax.process_count()} "
        f"loss={loss:.6f} step={int(jax.device_get(state.step))} l2={l2:.4f}",
        flush=True,
    )


if __name__ == "__main__":
    main(int(sys.argv[1]), int(sys.argv[2]))
