"""obs/ subsystem tests: the run-event bus, span tracing, the flight
recorder, and ``tools/run_report.py`` — plus the satellite paths (run_id
stamped into manifests and legacy jsonl records, the checkpoint-writer
queue-depth gauge, supervisor event hooks, ``--check`` validation).

The headline (ISSUE 5 acceptance) is
``test_e2e_faulted_run_events_validate``: the PR 3 nan_grad fault harness
plus an injected preemption, end to end through the real Trainer — every
event kind the run emits parses against the versioned schema, the
Chrome-trace export is valid JSON with strictly nested, monotonically
ordered spans per thread, and the checkpoint manifest / health.jsonl /
goodput.jsonl all carry the run identity the unified timeline joins on.
"""

import json
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))

from distributed_training_comparison_tpu import obs
from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.health import load_health_events
from distributed_training_comparison_tpu.obs.bus import EventBus
from distributed_training_comparison_tpu.obs.spans import SpanRecorder
from distributed_training_comparison_tpu.resilience import (
    EXIT_PREEMPTED,
    Preempted,
    Supervisor,
    load_goodput_records,
    read_manifest,
)
from distributed_training_comparison_tpu.train import AsyncCheckpointer, Trainer

from test_train import TinyNet

BASE_ARGS = [
    "--synthetic-data",
    "--limit-examples", "640",   # 576 train examples -> 18 steps/epoch @32
    "--batch-size", "32",
    "--epoch", "3",
    "--save-last-min-secs", "0",
    "--no-progress",
    "--seed", "7",
    "--eval-step", "1000",
]


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    """Each test gets a pristine process-current bus/recorder and no
    inherited run-id environment (the supervisor seam)."""
    monkeypatch.delenv(obs.RUN_ID_ENV, raising=False)
    monkeypatch.delenv(obs.ATTEMPT_ENV, raising=False)
    obs.reset()
    obs.set_recorder(None)
    yield
    obs.reset()
    obs.set_recorder(None)


# ------------------------------------------------------------------- bus


def test_emit_buffers_before_bind_and_flushes(tmp_path):
    for kind in ("alpha", "beta", "gamma"):  # embedder kinds: registered
        obs.register_kind(kind)
    bus = EventBus(run_id="r" * 16, attempt=2, process_index=0)
    bus.emit("alpha", epoch=0, note="early")
    bus.emit("beta", step=5)
    path = bus.bind_dir(tmp_path)
    bus.emit("gamma")
    bus.close()
    assert path == tmp_path / obs.EVENTS_NAME
    events = obs.load_events(path)
    assert [e["kind"] for e in events] == ["alpha", "beta", "gamma"]
    # construction-time events keep their original (pre-bind) timestamps
    assert events[0]["t_mono"] <= events[1]["t_mono"] <= events[2]["t_mono"]
    for ev in events:
        assert obs.validate_event(ev) == []
        assert ev["run_id"] == "r" * 16 and ev["attempt"] == 2


def test_events_filename_per_process():
    assert obs.events_filename(0) == "events.jsonl"
    assert obs.events_filename(3) == "events-p3.jsonl"
    from distributed_training_comparison_tpu.obs import trace_filename

    assert trace_filename(0, 0) == "trace.json"
    assert trace_filename(2, 0) == "trace-a2.json"
    assert trace_filename(2, 1) == "trace-a2-p1.json"
    assert obs.crash_dump_filename(0, 0) == "crash_dump.json"
    assert obs.crash_dump_filename(1, 0) == "crash_dump-a1.json"
    assert obs.crash_dump_filename(1, 2) == "crash_dump-a1-p2.json"


def test_crash_dump_per_attempt_never_clobbers(tmp_path):
    """A relaunched attempt aborting in the SAME version dir (auto-resume)
    must not overwrite the previous attempt's forensics."""
    first = EventBus(run_id="a" * 16, attempt=0)
    first.emit("tick", step=1)
    first.dump_crash("attempt 0 abort", directory=tmp_path)
    second = EventBus(run_id="a" * 16, attempt=1)
    second.emit("tock", step=2)
    path = second.dump_crash("attempt 1 abort", directory=tmp_path)
    assert path == tmp_path / "crash_dump-a1.json"
    assert json.loads(
        (tmp_path / obs.CRASH_DUMP_NAME).read_text()
    )["reason"] == "attempt 0 abort"
    assert json.loads(path.read_text())["reason"] == "attempt 1 abort"


def test_payload_coercion_numpy_and_paths(tmp_path):
    obs.register_kind("mix")
    bus = EventBus()
    bus.bind_dir(tmp_path)
    bus.emit(
        "mix",
        f32=np.float32(1.5),
        i64=np.int64(7),
        arr=np.arange(3),
        where=tmp_path,
        tags={"a", },
    )
    bus.close()
    (ev,) = obs.load_events(tmp_path / "events.jsonl")
    p = ev["payload"]
    assert p["f32"] == 1.5 and p["i64"] == 7 and p["arr"] == [0, 1, 2]
    assert p["tags"] == ["a"] and str(tmp_path) in p["where"]
    assert obs.validate_event(ev) == []


def test_flight_recorder_ring_bounded_and_first_dump_wins(tmp_path):
    bus = EventBus(ring_size=4)
    for i in range(10):
        bus.emit("tick", step=i)
    ring = bus.ring_events()
    assert len(ring) == 4 and [e["step"] for e in ring] == [6, 7, 8, 9]
    path = bus.dump_crash("specific abort", directory=tmp_path)
    # the generic unhandled-exception net must not overwrite the abort's
    # specific reason
    again = bus.dump_crash("generic re-raise", directory=tmp_path / "other")
    assert again == path
    dump = json.loads((tmp_path / obs.CRASH_DUMP_NAME).read_text())
    assert dump["reason"] == "specific abort"
    assert [e["step"] for e in dump["ring"]] == [6, 7, 8, 9]
    assert not (tmp_path / "other").exists()


def test_dump_crash_carries_exception(tmp_path):
    bus = EventBus()
    try:
        raise ValueError("boom")
    except ValueError as e:
        bus.dump_crash("unhandled", exc=e, directory=tmp_path)
    dump = json.loads((tmp_path / obs.CRASH_DUMP_NAME).read_text())
    assert dump["exception"]["type"] == "ValueError"
    assert "boom" in dump["exception"]["message"]
    assert any("ValueError" in ln for ln in dump["exception"]["traceback"])


def test_unbound_bus_dump_has_nowhere_to_write():
    bus = EventBus()
    bus.emit("tick")
    assert bus.dump_crash("no dir") is None


def test_persist_false_keeps_ring_only(tmp_path):
    """--no-obs buses never buffer pending lines (they will never be
    bound, so a pending list would grow for the whole run) — but the
    flight-recorder ring still records."""
    bus = EventBus(ring_size=4, persist=False)
    for i in range(10):
        bus.emit("tick", step=i)
    assert len(bus.ring_events()) == 4
    assert bus._pending == []
    # a late bind (not the --no-obs path, but legal) starts fresh: only
    # post-bind events land in the file
    bus.bind_dir(tmp_path)
    bus.emit("late")
    bus.close()
    assert [e["kind"] for e in obs.load_events(tmp_path / "events.jsonl")] == [
        "late"
    ]


def test_load_events_tolerates_torn_tail(tmp_path):
    path = tmp_path / "events.jsonl"
    good = json.dumps({"kind": "a"})
    path.write_text(good + "\n" + good + "\n" + '{"kind": "tor')  # torn append
    assert len(obs.load_events(path)) == 2
    assert obs.load_events(tmp_path / "missing.jsonl") == []


def test_reset_guard_spares_a_successor_bus():
    first = obs.configure(run_id="a" * 16)
    second = obs.configure(run_id="b" * 16)
    obs.reset(first)  # stale closer: must NOT tear down the successor
    assert obs.current_bus() is second
    obs.reset(second)
    assert obs.current_bus() is not second  # fresh default after real reset


def test_current_bus_inherits_environment(monkeypatch):
    monkeypatch.setenv(obs.RUN_ID_ENV, "e" * 16)
    monkeypatch.setenv(obs.ATTEMPT_ENV, "5")
    obs.reset()
    bus = obs.current_bus()
    assert bus.run_id == "e" * 16 and bus.attempt == 5


def test_default_bus_is_ring_only():
    """A never-configured bus may never be bound: emits must stay one
    deque append each, never an unbounded pending list (the library-
    embedder contract in obs/__init__.py)."""
    bus = obs.current_bus()
    for i in range(600):
        bus.emit("tick", step=i)
    assert bus._pending == []
    assert len(bus.ring_events()) == obs.bus.RING_SIZE_DEFAULT


# ---------------------------------------------------------------- schema


def test_validate_event_accepts_the_canonical_shape():
    ev = EventBus(run_id="f" * 16).emit("run_start", epoch=1, step=2, x=1)
    assert obs.validate_event(ev) == []
    # embedder kinds are admitted through the registry, not by accident
    ev2 = EventBus(run_id="f" * 16).emit("my_embedder_kind")
    assert obs.validate_event(ev2) != []
    obs.register_kind("my_embedder_kind")
    assert obs.validate_event(ev2) == []


@pytest.mark.parametrize(
    "mutate, expect",
    [
        (lambda e: e.pop("run_id"), "missing required field 'run_id'"),
        (lambda e: e.pop("t_wall"), "missing required field 't_wall'"),
        (lambda e: e.update(v=99), "schema version 99 != 1"),
        (lambda e: e.update(extra=1), "unknown field 'extra'"),
        (lambda e: e.update(kind=7), "field 'kind' has type int"),
        (lambda e: e.update(attempt=True), "field 'attempt' has type bool"),
        (lambda e: e.update(attempt=-1), "field 'attempt' is negative"),
        (lambda e: e.update(run_id=""), "run_id is empty"),
        (lambda e: e.update(payload=[1]), "payload has type list"),
        (
            lambda e: e.update(kind="unregistered_drift"),
            "kind 'unregistered_drift' is not registered "
            "(obs.bus.KNOWN_KINDS / register_kind)",
        ),
    ],
)
def test_validate_event_catches_violations(mutate, expect):
    ev = EventBus(run_id="f" * 16).emit("run_start", epoch=1, x=1)
    mutate(ev)
    assert expect in obs.validate_event(ev)


def test_validate_event_rejects_non_objects():
    assert obs.validate_event([1, 2]) != []
    assert obs.validate_event("nope") != []


# ----------------------------------------------------------------- spans


def _assert_strictly_nested(trace: dict):
    """Per thread: spans are monotonically ordered by begin time and every
    span either contains or is disjoint from every other (no partial
    overlap) — the invariant the per-thread context-manager stacks
    guarantee by construction."""
    lanes: dict = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "X":
            lanes.setdefault(ev["tid"], []).append(ev)
    assert lanes, "trace has no complete events"
    for evs in lanes.values():
        last_ts = -1.0
        stack: list = []
        for ev in evs:
            t0, t1 = ev["ts"], ev["ts"] + ev["dur"]
            assert t0 >= last_ts  # monotonically ordered
            last_ts = t0
            while stack and stack[-1] <= t0:
                stack.pop()
            if stack:
                assert t1 <= stack[-1]  # strictly inside the enclosing span
            stack.append(t1)


def test_span_nesting_across_threads_and_chrome_export(tmp_path):
    rec = SpanRecorder(process_index=0)

    def worker():
        with rec.span("outer_w"):
            with rec.span("inner_w"):
                time.sleep(0.002)

    t = threading.Thread(target=worker, name="lane-b")
    with rec.span("outer", epoch=1):
        t.start()
        with rec.span("inner"):
            time.sleep(0.002)
        with rec.span("inner2"):
            pass
        t.join()
    spans = rec.spans()
    assert {s["name"] for s in spans} == {
        "outer", "inner", "inner2", "outer_w", "inner_w",
    }
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["depth"] == 1 and by_name["outer"]["depth"] == 0
    # the worker's stack is its own: depth restarts at 0 on the new thread
    assert by_name["outer_w"]["depth"] == 0
    assert by_name["outer"]["args"] == {"epoch": 1}

    path = obs.write_chrome_trace(tmp_path / "trace.json", rec, label="t")
    trace = json.loads(path.read_text())  # valid JSON
    _assert_strictly_nested(trace)
    names = {
        e["args"]["name"]
        for e in trace["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "lane-b" in names  # lanes carry the thread names


def test_span_recorder_bounded(tmp_path):
    rec = SpanRecorder(max_spans=3)
    for _ in range(5):
        with rec.span("s"):
            pass
    assert len(rec.spans()) == 3 and rec.dropped == 2
    # a capped trace announces its truncation in the process lane name —
    # Perfetto readers must not mistake the cutoff for the run going idle
    trace = json.loads(
        obs.write_chrome_trace(tmp_path / "t.json", rec).read_text()
    )
    (pname,) = [
        e for e in trace["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    ]
    assert "TRUNCATED: 2 spans dropped" in pname["args"]["name"]


def test_exception_inside_span_still_closes_it():
    rec = SpanRecorder()
    with pytest.raises(ValueError):
        with rec.span("doomed"):
            raise ValueError("x")
    (s,) = rec.spans()
    assert s["name"] == "doomed" and s["t1"] >= s["t0"]


def test_step_time_meter_mirrors_phases_as_spans():
    from distributed_training_comparison_tpu.utils.meters import StepTimeMeter

    rec = SpanRecorder()
    meter = StepTimeMeter(tracer=rec)
    with meter.phase("dispatch"):
        pass
    with meter.phase("compute"):
        pass
    assert [s["name"] for s in rec.spans()] == ["dispatch", "compute"]
    assert meter.seconds["dispatch"] >= 0.0


def test_annotations_are_nullcontexts_outside_profiling():
    # the step/trace annotation helpers must be inert (and cheap) when no
    # profiler session is active — they wrap every chunk dispatch
    with obs.step_annotation(7):
        pass
    rec = SpanRecorder()
    rec.annotate = True  # TraceAnnotation path, no active trace session
    with rec.span("annotated"):
        pass
    assert rec.spans()[0]["name"] == "annotated"


# --------------------------------------------- checkpoint-writer satellite


def test_async_checkpointer_queue_depth_gauge():
    writer = AsyncCheckpointer()
    release = threading.Event()
    try:
        writer.submit(lambda: release.wait(5), key="a")
        writer.submit(lambda: None, key="b")
        deadline = time.monotonic() + 2
        while writer.stats()["queue_depth"] < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        release.set()
        writer.wait()
        stats = writer.stats()
        assert stats["queue_depth"] == 0
        assert set(stats) == {"busy_s", "alive_s", "busy_frac", "queue_depth"}
    finally:
        release.set()
        writer.close()


def test_async_checkpointer_superseded_jobs_drain_depth():
    writer = AsyncCheckpointer()
    ran = []
    try:
        for i in range(4):  # same key: later submits supersede earlier ones
            writer.submit(lambda i=i: ran.append(i), key="last")
        writer.wait()
        assert writer.stats()["queue_depth"] == 0  # superseded slots drained
        assert ran  # at least the newest job ran
    finally:
        writer.close()


# ----------------------------------------------------- supervisor events


def test_supervisor_emits_attempt_and_backoff_events():
    rcs = iter([EXIT_PREEMPTED, 1, 0])
    seen: list = []
    sup = Supervisor(
        ["true"],
        max_restarts=3,
        backoff_base=0.01,
        runner=lambda cmd, env: next(rcs),
        sleep=lambda s: None,
        log=lambda msg: None,
        events=lambda kind, **p: seen.append((kind, p)),
    )
    sup.run()
    kinds = [k for k, _ in seen]
    assert kinds == [
        "attempt_start", "attempt_end",   # preempted -> immediate relaunch
        "attempt_start", "attempt_end", "backoff",  # crash -> backoff
        "attempt_start", "attempt_end",   # success
    ]
    ends = [p for k, p in seen if k == "attempt_end"]
    assert ends[0]["preempted"] is True and ends[0]["returncode"] == EXIT_PREEMPTED
    assert ends[2]["returncode"] == 0


def test_supervisor_emits_give_up():
    seen: list = []
    sup = Supervisor(
        ["true"],
        max_restarts=0,
        runner=lambda cmd, env: 9,
        sleep=lambda s: None,
        log=lambda msg: None,
        events=lambda kind, **p: seen.append(kind),
    )
    sup.run()
    assert seen == ["attempt_start", "attempt_end", "give_up"]


# ----------------------------------------------------------- config flags


def test_obs_flags_defaults_and_validation():
    hp = load_config("tpu", ["--synthetic-data"])
    assert hp.obs is True and hp.flight_recorder_size == 256
    hp = load_config("tpu", ["--synthetic-data", "--no-obs"])
    assert hp.obs is False
    with pytest.raises(SystemExit):
        load_config("tpu", ["--flight-recorder-size", "0"])


# ------------------------------------------------- trainer e2e (acceptance)


def _fit(tmp_path, extra=()):
    hp = load_config(
        "tpu", argv=BASE_ARGS + ["--ckpt-path", str(tmp_path), *extra]
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    try:
        trainer.fit()
    finally:
        trainer.close()
    return trainer


@pytest.mark.obs
def test_e2e_faulted_run_events_validate(tmp_path):
    """ISSUE 5 acceptance (single-attempt leg): the PR 3 nan_grad harness
    plus an injected preemption → every emitted event kind validates
    against the versioned schema, the run identity is stamped into the
    manifest and the legacy jsonl records, and the Chrome-trace export is
    valid JSON with strictly nested spans per thread."""
    hp = load_config(
        "tpu",
        argv=BASE_ARGS + [
            "--ckpt-path", str(tmp_path),
            "--fault-plan", "nan_grad@epoch=1;preempt@epoch=2",
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    with pytest.raises(Preempted):
        trainer.fit()
    trainer.close()
    vdir = tmp_path / "version-0"

    events = obs.load_events(vdir / "events.jsonl")
    assert events, "faulted run emitted no events"
    for ev in events:
        assert obs.validate_event(ev) == [], ev
    kinds = {e["kind"] for e in events}
    assert {
        "run_start", "epoch_start", "epoch_end", "skip", "rollback",
        "preempt", "writer", "goodput",
    } <= kinds
    run_id = events[0]["run_id"]
    assert all(e["run_id"] == run_id for e in events)
    # one emitter per subsystem: the rollback cause and the preemption
    # point are attributable straight off the stream
    (rb,) = [e for e in events if e["kind"] == "rollback"]
    assert "bad steps" in rb["payload"]["reason"]
    (pre,) = [e for e in events if e["kind"] == "preempt"]
    assert pre["epoch"] == 2 and pre["payload"]["mid_epoch"] is False

    # satellite: the run identity rides the checkpoint manifest and the
    # legacy per-subsystem jsonl records (old records stay valid: the
    # loaders don't require the stamp)
    manifest = read_manifest(vdir / "last.ckpt")
    assert manifest["run_id"] == run_id and manifest["attempt"] == 0
    health = load_health_events(vdir / "health.jsonl")
    assert health and all(h["run_id"] == run_id for h in health)
    (record,) = load_goodput_records(vdir / "goodput.jsonl")
    assert record["run_id"] == run_id and record["attempt"] == 0
    assert "queue_depth" in record["ckpt_writer"]  # the new writer gauge

    # span timeline: valid JSON, strictly nested, the trainer + writer
    # lanes both present
    trace = json.loads((vdir / "trace.json").read_text())
    _assert_strictly_nested(trace)
    span_names = {
        e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"
    }
    assert {"epoch", "eval", "rollback", "ckpt_write"} <= span_names
    lanes = {
        e["tid"] for e in trace["traceEvents"] if e.get("ph") == "X"
    }
    assert len(lanes) >= 2  # trainer loop + checkpoint writer


@pytest.mark.obs
def test_e2e_abort_leaves_crash_dump(tmp_path):
    """A rollback wanted with the budget already spent aborts — and the
    abort dumps the flight recorder: crash_dump.json holds the final ring
    with the skip trail and the abort reason."""
    with pytest.raises(FloatingPointError, match="non-finite"):
        _fit(
            tmp_path,
            extra=[
                "--fault-plan", "nan_grad@epoch=1",
                "--health-max-rollbacks", "0",
            ],
        )
    dump = json.loads((tmp_path / "version-0" / "crash_dump.json").read_text())
    assert "non-finite" in dump["reason"]
    ring_kinds = {e["kind"] for e in dump["ring"]}
    assert {"run_start", "skip", "abort"} <= ring_kinds
    for ev in dump["ring"]:
        assert obs.validate_event(ev) == []


@pytest.mark.obs
def test_e2e_run_id_inherited_from_supervisor_env(tmp_path, monkeypatch):
    """The supervisor hands every attempt the run id + restart index via
    the environment; the Trainer's bus, the manifest, and every record
    must carry them verbatim."""
    monkeypatch.setenv(obs.RUN_ID_ENV, "c0ffee0123456789")
    monkeypatch.setenv(obs.ATTEMPT_ENV, "3")
    _fit(tmp_path)
    events = obs.load_events(tmp_path / "version-0" / "events.jsonl")
    assert events
    assert all(
        e["run_id"] == "c0ffee0123456789" and e["attempt"] == 3
        for e in events
    )
    manifest = read_manifest(tmp_path / "version-0" / "last.ckpt")
    assert manifest["run_id"] == "c0ffee0123456789" and manifest["attempt"] == 3


@pytest.mark.obs
def test_no_obs_keeps_ring_but_writes_no_files(tmp_path):
    trainer = _fit(tmp_path, extra=["--no-obs"])
    vdir = tmp_path / "version-0"
    assert not (vdir / "events.jsonl").exists()
    assert not (vdir / "trace.json").exists()
    # the flight recorder still records (a crash would still dump)
    assert trainer.bus.ring_events()


# ------------------------------------------------------------- run_report


def _write_events(path, events):
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _mk_run(root, run_id="ab" * 8, goodput=(6.0, 10.0)):
    """A synthetic two-attempt supervised run layout: supervisor events at
    the root, per-attempt events in the version dirs."""
    sup = EventBus(run_id=run_id)
    for kind, payload in (
        ("attempt_start", {"attempt": 0}),
        ("attempt_end", {"attempt": 0, "returncode": 75, "preempted": True}),
        ("attempt_start", {"attempt": 1}),
        ("attempt_end", {"attempt": 1, "returncode": 0, "preempted": False}),
    ):
        sup.emit(kind, **payload)
    _write_events(root / "events.jsonl", sup.ring_events())
    step_s, wall_s = goodput
    for attempt, n_epochs in ((0, 2), (1, 2)):
        bus = EventBus(run_id=run_id, attempt=attempt)
        bus.emit("run_start", epoch=0)
        for e in range(n_epochs):
            bus.emit("epoch_start", epoch=e)
            bus.emit("epoch_end", epoch=e, secs=1.0)
        if attempt == 0:
            bus.emit("rollback", epoch=1, reason="3 consecutive bad steps")
            bus.emit("skip", epoch=1, count=3)
            bus.emit("preempt", epoch=1, step=36, mid_epoch=False)
        bus.emit("writer", epoch=n_epochs - 1, busy_frac=0.25, queue_depth=1)
        bus.emit(
            "goodput",
            step_s=step_s, wall_s=wall_s,
            step_breakdown={"h2d_wait_s": 0.5},
        )
        _write_events(
            root / "version-0" / obs.events_filename(attempt and 1),
            bus.ring_events(),
        )
    return root


@pytest.mark.obs
def test_run_report_merges_summarizes_and_formats(tmp_path):
    import run_report

    _mk_run(tmp_path)
    events, files = run_report.load_run(tmp_path)
    assert len(files) == 3  # supervisor + two per-attempt files
    walls = [e["t_wall"] for e in events]
    assert walls == sorted(walls)  # one wall-clock-ordered timeline
    s = run_report.summarize(events)
    assert set(s["attempts"]) == {0, 1}
    assert s["epochs"] == 4 and s["rollbacks"] == 1 and s["preemptions"] == 1
    assert s["attempts"][0]["rollback_causes"] == [
        "epoch 1: 3 consecutive bad steps"
    ]
    assert len(s["supervisor"]) == 4
    assert s["goodput_frac"] == pytest.approx(12.0 / 20.0)
    text = run_report.format_summary("x", s)
    assert "2 attempt(s)" in text and "3 consecutive bad steps" in text
    timeline = run_report.format_timeline(events, tail=0)
    assert "preempt" in timeline and "a1/p0" in timeline
    diff = run_report.format_diff("a", s, "b", run_report.summarize(events))
    assert "rollbacks" in diff


@pytest.mark.obs
def test_run_report_summarize_counts_each_multihost_event_once(tmp_path):
    """Every process of a multi-host attempt emits the same trainer and
    watchdog events into its own events-p{i}.jsonl; the merged summary
    must count each occurrence once, not once per process."""
    import run_report

    root = _mk_run(tmp_path)
    # mirror attempt 1's events as a second process of attempt 1
    bus = EventBus(run_id="ab" * 8, attempt=1, process_index=1)
    for e in range(2):
        bus.emit("epoch_start", epoch=e)
        bus.emit("epoch_end", epoch=e, secs=1.0)
    bus.emit("writer", epoch=1, busy_frac=0.25, queue_depth=1)
    bus.emit("goodput", step_s=6.0, wall_s=10.0)
    _write_events(root / "version-0" / "events-a1-p1.jsonl", bus.ring_events())
    events, _ = run_report.load_run(root)
    s = run_report.summarize(events)
    assert s["epochs"] == 4  # not 6: process 1's epoch_ends aren't re-counted
    assert s["attempts"][1]["epochs"] == 2
    assert s["attempts"][1]["processes"] == {0, 1}  # the lane IS recorded
    assert s["rollbacks"] == 1


@pytest.mark.obs
def test_run_report_check_catches_violations(tmp_path):
    import run_report

    good = _mk_run(tmp_path / "good")
    assert run_report.check_run(good) == []
    bad_dir = tmp_path / "bad"
    bad_ev = EventBus(run_id="cd" * 8).emit("ok")
    bad_ev2 = dict(bad_ev, v=99)
    _write_events(bad_dir / "events.jsonl", [bad_ev, bad_ev2])
    with open(bad_dir / "events.jsonl", "a") as f:
        f.write('{"torn')
    problems = run_report.check_run(bad_dir)
    assert any("schema version 99" in p for p in problems)
    assert any("unparseable" in p for p in problems)
    assert run_report.check_run(tmp_path / "missing") != []  # no files = fail
    # the CLI contract bench legs rely on: nonzero exit on violations
    assert run_report.main([str(bad_dir), "--check"]) == 1
    assert run_report.main([str(good), "--check"]) == 0


@pytest.mark.obs
def test_run_report_diff_cli(tmp_path, capsys):
    import run_report

    a = _mk_run(tmp_path / "a", goodput=(6.0, 10.0))
    b = _mk_run(tmp_path / "b", run_id="ef" * 8, goodput=(9.0, 10.0))
    assert run_report.main([str(a), str(b), "--diff"]) == 0
    out = capsys.readouterr().out
    assert "goodput %" in out
    assert run_report.main([str(a), "--diff"]) == 2  # needs exactly two


# ----------------------------------------------------------------- serve


def test_serve_metrics_emit_event_validates():
    from distributed_training_comparison_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    for _ in range(4):
        m.record_request_done(0.003)
    m.record_batch(batch_size=4, queue_depth=2)
    m.record_shed()
    ev = m.emit_event(EventBus(run_id="ad" * 8))
    assert ev["kind"] == "serve"
    assert ev["payload"]["completed"] == 4 and ev["payload"]["shed"] == 1
    assert obs.validate_event(ev) == []
