"""Cold-start child for tests/test_serve_fleet.py: one REAL fresh
serving process against a shared persisted AOT store.

Builds a TinyNet serve engine (mirroring ``tests/test_train.TinyNet`` —
defined inline so importing this worker never imports a test module)
with a CompileMonitor bound to ``events_dir`` and a
``PersistedServeCache`` at ``aot_dir``, warms the ladder, serves one
smoke batch, and prints one JSON line with the engine counters.  The
parent judges the STREAM (compile events in ``events_dir``), not this
self-report: the first child must pay real compiles and store, the
second must deserialize by fingerprint and compile nothing.

Usage: ``python tests/serve_cold_worker.py EVENTS_DIR AOT_DIR``
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(events_dir: str, aot_dir: str) -> None:
    import flax.linen as lnn
    import jax.numpy as jnp
    import numpy as np

    from distributed_training_comparison_tpu import obs
    from distributed_training_comparison_tpu.serve import ServeEngine
    from distributed_training_comparison_tpu.utils import PersistedServeCache

    class TinyNet(lnn.Module):
        num_classes: int = 10
        dtype: jnp.dtype = jnp.float32

        @lnn.compact
        def __call__(self, x, train: bool = False):
            x = x.astype(self.dtype)
            x = lnn.Conv(
                8, (3, 3), strides=2, use_bias=False, dtype=self.dtype
            )(x)
            x = lnn.BatchNorm(
                use_running_average=not train, dtype=self.dtype
            )(x)
            x = lnn.relu(x)
            x = jnp.mean(x, axis=(1, 2))
            return lnn.Dense(
                self.num_classes, dtype=self.dtype
            )(x).astype(jnp.float32)

    bus = obs.configure(run_id=obs.new_run_id())
    bus.bind_dir(events_dir)
    registry = obs.MetricRegistry()
    monitor = obs.CompileMonitor(bus=bus, registry=registry)
    t0 = time.perf_counter()
    engine = ServeEngine(
        model=TinyNet(),
        buckets=(2, 4),
        precision="fp32",
        image_size=16,
        monitor=monitor,
        aot_cache=PersistedServeCache(aot_dir),
    )
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    engine.predict_logits(np.zeros((3, 16, 16, 3), np.uint8))
    registry.flush(bus)
    stats = engine.stats()
    print(json.dumps({
        "warmup_s": round(warmup_s, 3),
        "compiles": stats["compiles"],
        "persisted_hits": stats["persisted_hits"],
        "aot_cache": stats["aot_cache"],
    }))
    obs.reset(bus)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
