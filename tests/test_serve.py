"""Serving subsystem tests on the 8-device CPU mesh (tier-1 fast).

Covers the serve/ contracts end to end: bucket selection + padding
semantics, the zero-recompile guarantee under ragged open-loop traffic
(asserted through the engine's own compile/cache counters), typed
load-shed and deadline errors, checkpoint fidelity (engine logits ==
the Trainer's restored-best-checkpoint logits, engine accuracy ==
``Trainer.test``), and the flag surface.
"""

import time

import jax
import numpy as np
import pytest

from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.data import get_datasets
from distributed_training_comparison_tpu.data.augment import normalize_images
from distributed_training_comparison_tpu.serve import (
    BatcherClosed,
    DeadlineExceeded,
    MicroBatcher,
    QueueOverflow,
    ServeEngine,
    ServeError,
    ServeMetrics,
    closed_loop,
    open_loop,
    request_pool,
)
from distributed_training_comparison_tpu.train import Trainer
from distributed_training_comparison_tpu.train.checkpoint import (
    find_serving_checkpoint,
)

from test_train import TinyNet

IMG = 16  # request image edge for the engine-only tests


@pytest.fixture(scope="module")
def engine():
    eng = ServeEngine(
        model=TinyNet(num_classes=10),
        buckets=(2, 4, 8),
        precision="fp32",
        image_size=IMG,
    )
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def images():
    return request_pool(64, image_size=IMG, seed=0)


# --------------------------------------------------------------- buckets


def test_bucket_selection(engine):
    assert engine.bucket_for(1) == 2
    assert engine.bucket_for(2) == 2
    assert engine.bucket_for(3) == 4
    assert engine.bucket_for(8) == 8
    with pytest.raises(ValueError, match="largest bucket"):
        engine.bucket_for(9)


def test_predict_chunks_past_max_bucket(engine, images):
    before = dict(engine.stats()["bucket_counts"])
    out = engine.predict_logits(images[:19])  # 8 + 8 + 3 → buckets 8,8,4
    assert out.shape == (19, 10) and out.dtype == np.float32
    after = engine.stats()["bucket_counts"]
    assert after[8] - before[8] == 2 and after[4] - before[4] == 1


def test_empty_batch_keeps_logits_rank(engine):
    out = engine.predict_logits(np.zeros((0, IMG, IMG, 3), np.uint8))
    assert out.shape == (0, 10) and out.dtype == np.float32


def test_padding_rows_do_not_change_logits(engine, images):
    """A size-3 request padded into the 4-bucket must yield the same rows
    as the same images inside a full bucket (eval-mode per-example
    independence)."""
    ragged = engine.predict_logits(images[:3])
    full = engine.predict_logits(images[:4])
    np.testing.assert_allclose(ragged, full[:3], rtol=0, atol=1e-6)


def test_ragged_traffic_never_recompiles_after_warmup(engine, images):
    compiles = engine.stats()["compiles"]
    assert compiles == len(engine.buckets)  # warmup compiled the ladder
    rng = np.random.default_rng(0)
    for n in rng.integers(1, 9, size=16):
        engine.predict_logits(images[: int(n)])
    stats = engine.stats()
    assert stats["compiles"] == compiles  # ZERO recompiles on ragged sizes
    assert stats["cache_hits"] >= 16


# ---------------------------------------------------- batcher + shedding


class _SlowStubEngine:
    """Engine stand-in with a controllable service time (no device work)."""

    max_bucket = 8

    def __init__(self, delay_s: float = 0.05):
        self.delay_s = delay_s
        self.calls = []

    def predict_logits(self, imgs):
        time.sleep(self.delay_s)
        self.calls.append(len(imgs))
        return np.zeros((len(imgs), 4), np.float32)


def test_batcher_coalesces_and_completes():
    eng = _SlowStubEngine(delay_s=0.01)
    with MicroBatcher(eng, max_wait_ms=20, queue_limit=32) as b:
        futs = [b.submit(np.zeros((4, 4, 3), np.uint8)) for _ in range(5)]
        rows = [f.result(timeout=5) for f in futs]
    assert all(r.shape == (4,) for r in rows)
    assert sum(eng.calls) == 5
    assert max(eng.calls) > 1  # the window actually coalesced requests


def test_queue_overflow_is_typed_and_counted():
    eng = _SlowStubEngine(delay_s=0.2)  # worker busy → queue builds
    m = ServeMetrics()
    b = MicroBatcher(eng, max_wait_ms=1, queue_limit=4, metrics=m)
    try:
        b.submit(np.zeros((4, 4, 3), np.uint8))  # occupies the worker
        time.sleep(0.05)
        with pytest.raises(QueueOverflow) as ei:
            for _ in range(10):
                b.submit(np.zeros((4, 4, 3), np.uint8))
        assert isinstance(ei.value, ServeError)  # typed hierarchy
        assert m.shed >= 1
    finally:
        b.close()


def test_deadline_expiry_is_typed():
    eng = _SlowStubEngine(delay_s=0.15)
    m = ServeMetrics()
    b = MicroBatcher(eng, max_wait_ms=1, queue_limit=32, metrics=m)
    try:
        blocker = b.submit(np.zeros((4, 4, 3), np.uint8))
        time.sleep(0.02)  # ensure the blocker's batch dispatched first
        doomed = b.submit(np.zeros((4, 4, 3), np.uint8), deadline_ms=1.0)
        blocker.result(timeout=5)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=5)
        assert m.expired == 1
    finally:
        b.close()


def test_submit_after_close_raises():
    b = MicroBatcher(_SlowStubEngine(0.0), max_wait_ms=1, queue_limit=4)
    b.close()
    with pytest.raises(BatcherClosed):
        b.submit(np.zeros((4, 4, 3), np.uint8))


# ------------------------------------------------------------- load gens


def test_closed_and_open_loop_reports(engine, images):
    m = ServeMetrics()
    with MicroBatcher(engine, max_wait_ms=5, queue_limit=64, metrics=m) as b:
        closed = closed_loop(b, images, num_requests=24, concurrency=4)
        compiles = engine.stats()["compiles"]
        opened = open_loop(b, images, rate_rps=400.0, num_requests=24, seed=1)
    for rep in (closed, opened):
        assert rep["offered"] == 24
        assert rep["completed"] + rep["shed"] + rep["expired"] + rep["failed"] == 24
        assert rep["completed"] > 0
        assert rep["latency_ms"]["p50"] <= rep["latency_ms"]["p99"]
    # the acceptance contract: ragged open-loop traffic, zero recompiles
    assert engine.stats()["compiles"] == compiles
    s = m.summary()
    assert s["completed"] == closed["completed"] + opened["completed"]
    assert s["mean_batch_size"] >= 1.0


def test_metrics_tensorboard_roundtrip(tmp_path):
    m = ServeMetrics()
    m.record_request_done(0.010)
    m.record_request_done(0.020)
    m.record_batch(2, 0)
    m.record_shed()
    m.write_tensorboard(tmp_path)
    assert list(tmp_path.glob("events.out.tfevents.*"))
    s = m.summary()
    assert s["completed"] == 2 and s["shed"] == 1
    assert 10.0 <= s["latency_ms"]["p50"] <= 20.0


# ------------------------------------------- checkpoint fidelity (e2e)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """One tiny fit() whose best checkpoint the engine serves."""
    tmp = tmp_path_factory.mktemp("serve_ckpt")
    hp = load_config(
        "tpu",
        argv=[
            "--synthetic-data", "--limit-examples", "256",
            "--batch-size", "64", "--epoch", "1", "--eval-step", "2",
            "--lr", "0.05", "--ckpt-path", str(tmp),
        ],
    )
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    trainer.fit()
    results = trainer.test()  # loads the best checkpoint into trainer.state
    return hp, trainer, results, tmp


def test_engine_matches_trainer_on_restored_checkpoint(trained):
    hp, trainer, results, tmp = trained
    ckpt_path = find_serving_checkpoint(tmp)
    assert ckpt_path is not None and ckpt_path.name.startswith("best_model_")
    engine = ServeEngine(
        model=TinyNet(num_classes=100),
        checkpoint_path=ckpt_path,
        buckets=(64,),
        precision="fp32",
        image_size=32,
    )
    assert engine.checkpoint_meta is not None

    _, _, tst = get_datasets(hp)
    batch = tst.images[:64]
    got = engine.predict_logits(batch)
    want = np.asarray(
        trainer.state.apply_fn(
            {
                "params": trainer.state.params,
                "batch_stats": trainer.state.batch_stats,
            },
            normalize_images(batch),
            train=False,
        )
    )
    np.testing.assert_allclose(got, want, atol=1e-5)

    # whole-split accuracy through the engine == Trainer.test's top-1
    logits = engine.predict_logits(tst.images)
    top1 = 100.0 * float(
        np.mean(np.argmax(logits, axis=-1) == tst.labels)
    )
    assert abs(top1 - results["test_top1"]) < 1e-3
    trainer.close()


def test_engine_serves_last_ckpt_too(trained):
    """load_eval_variables accepts the resumable last.ckpt layout."""
    hp, _, _, tmp = trained
    last = next(tmp.glob("version-*/last.ckpt"))
    engine = ServeEngine(
        model=TinyNet(num_classes=100),
        checkpoint_path=last,
        buckets=(8,),
        precision="fp32",
        image_size=32,
    )
    out = engine.predict_logits(np.zeros((3, 32, 32, 3), np.uint8))
    assert out.shape == (3, 100) and np.isfinite(out).all()


# ---------------------------------------------------------- flag surface


def test_serve_flags_parse():
    hp = load_config(
        "tpu",
        argv=[
            "--serve", "--serve-buckets", "8,1,4,4",
            "--max-wait-ms", "3.5", "--queue-limit", "7",
            "--serve-rate", "100",
        ],
    )
    assert hp.serve is True
    assert hp.serve_buckets == (1, 4, 8)  # sorted, deduped
    assert hp.max_wait_ms == 3.5 and hp.queue_limit == 7


def test_serve_buckets_validation():
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--serve-buckets", "0,4"])
    with pytest.raises(SystemExit):
        load_config("tpu", argv=["--serve-buckets", "a,b"])
