"""End-to-end Trainer tests on the 8-device CPU mesh: fit → artifacts →
test → resume, with a tiny model standing in for the (CPU-prohibitive)
ResNet flagship.  This is the 'src/single slice end-to-end' of SURVEY.md §7
step 4, exercised hermetically."""

import numpy as np
import pytest

from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.train import Trainer

from test_train import TinyNet


def _hparams(tmp_path, extra=()):
    return load_config(
        "ddp",
        argv=[
            "--synthetic-data",
            "--limit-examples", "256",
            "--batch-size", "64",
            "--epoch", "2",
            "--eval-step", "2",
            "--lr", "0.05",
            "--ckpt-path", str(tmp_path),
            *extra,
        ],
    )


@pytest.fixture(scope="module")
def run_dir(tmp_path_factory):
    """One fit() shared by the artifact assertions below."""
    tmp_path = tmp_path_factory.mktemp("run")
    hp = _hparams(tmp_path)
    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    version = trainer.fit()
    results = trainer.test()
    trainer.close()
    return tmp_path, version, results, trainer


def test_fit_returns_version_and_artifacts(run_dir):
    tmp_path, version, results, _ = run_dir
    vdir = tmp_path / f"version-{version}"
    assert version == 0
    assert (vdir / "experiment.log").exists()
    assert (vdir / "hparams.yaml").exists()
    assert (vdir / "last.ckpt").exists()
    assert list(vdir.glob("best_model_*.ckpt"))
    assert list((vdir / "tb").glob("events.out.tfevents.*"))
    log = (vdir / "experiment.log").read_text()
    assert "start training" in log and "val acc" in log


def test_hparams_yaml_roundtrip(run_dir):
    yaml = pytest.importorskip("yaml")
    tmp_path, version, _, _ = run_dir
    loaded = yaml.safe_load((tmp_path / f"version-{version}" / "hparams.yaml").read_text())
    assert loaded["batch_size"] == 64 and loaded["backend"] == "ddp"


def test_test_metrics_shape(run_dir):
    _, _, results, _ = run_dir
    assert set(results) == {"test_loss", "test_top1", "test_top5"}
    assert 0.0 <= results["test_top1"] <= results["test_top5"] <= 100.0
    assert results["test_loss"] > 0


def test_auto_resume_continues_latest_run(run_dir):
    """--auto-resume must reuse the newest version dir + last.ckpt instead
    of starting a fresh version."""
    src_tmp, version, _, trainer = run_dir
    hp = _hparams(src_tmp, extra=["--auto-resume", "--epoch", "3"])
    t2 = Trainer(hp, model=TinyNet(num_classes=100))
    assert t2.start_epoch == 2
    assert t2.version == version  # same run continued, not a new version dir
    assert int(np.asarray(t2.state.step)) == int(np.asarray(trainer.state.step))
    t2.close()


def test_auto_resume_skips_newest_run_without_last_ckpt(run_dir, tmp_path):
    """If the newest version crashed before its first save, auto-resume must
    start fresh — not silently resume an older (completed) run in place."""
    import shutil

    src_tmp, version, _, _ = run_dir
    shutil.copytree(src_tmp / f"version-{version}", tmp_path / f"version-{version}")
    (tmp_path / f"version-{version + 1}").mkdir()  # crashed, no last.ckpt
    hp = _hparams(tmp_path, extra=["--auto-resume", "--epoch", "1"])
    t = Trainer(hp, model=TinyNet(num_classes=100))
    assert t.start_epoch == 0
    assert t.version == version + 2  # a fresh version dir
    t.close()


def test_explicit_resume_with_auto_flag_uses_fresh_version_dir(run_dir, tmp_path):
    """--resume PATH (even alongside --auto-resume) must write into a new
    version under --ckpt-path, never into the source run's directory."""
    src_tmp, version, _, _ = run_dir
    last = src_tmp / f"version-{version}" / "last.ckpt"
    hp = _hparams(tmp_path, extra=["--auto-resume", "--resume", str(last), "--epoch", "3"])
    t = Trainer(hp, model=TinyNet(num_classes=100))
    assert t.start_epoch == 2  # state restored from the source checkpoint
    assert t.version_dir.parent == tmp_path  # but artifacts go to a new dir
    t.close()


def test_auto_resume_without_checkpoint_starts_fresh(tmp_path):
    hp = _hparams(tmp_path, extra=["--auto-resume", "--epoch", "1"])
    t = Trainer(hp, model=TinyNet(num_classes=100))
    assert t.start_epoch == 0 and t.version == 0
    t.close()


def test_nan_loss_aborts_run(tmp_path):
    """Failure detection: a diverged epoch must abort with a pointer to the
    last saved state instead of training on."""
    hp = _hparams(tmp_path, extra=["--lr", "1e8"])  # guaranteed divergence
    t = Trainer(hp, model=TinyNet(num_classes=100))
    with pytest.raises(FloatingPointError, match="non-finite train loss"):
        t.fit()
    t.close()


def test_host_mode_chunk_invariance(tmp_path):
    """The chunked host-streaming path must produce a bit-identical loss
    trajectory for any --host-chunk-steps (keys fold from the global step
    index inside the scan), and its state must advance like the device
    path's."""
    losses = {}
    for chunk in (1, 4):
        hp = _hparams(
            tmp_path / f"c{chunk}",
            extra=["--data-mode", "host", "--host-chunk-steps", str(chunk)],
        )
        t = Trainer(hp, model=TinyNet(num_classes=100))
        ls, top1 = t._train_epoch_host(0)
        losses[chunk] = (ls, top1, int(np.asarray(t.state.step)))
        t.close()
    l1, t1, s1 = losses[1]
    l4, t4, s4 = losses[4]
    assert s1 == s4 == len(l1) == len(l4)
    assert t1 == t4
    np.testing.assert_array_equal(l1, l4)


def test_device_mode_chunk_invariance(tmp_path):
    """The chunked device path must produce a bit-identical loss trajectory
    and state for any --device-chunk-steps (the chunk recomputes the epoch
    permutation + key split the monolithic program derives — same contract
    the host chunk runner documents), including a remainder-sized chunk."""
    losses = {}
    for chunk in (0, 2, 3):  # 0 = whole epoch; 3 leaves a remainder of 1
        hp = _hparams(
            tmp_path / f"c{chunk}",
            extra=["--device-chunk-steps", str(chunk)],
        )
        t = Trainer(hp, model=TinyNet(num_classes=100))
        ls, top1 = t._train_epoch_device(0)
        losses[chunk] = (ls, top1, int(np.asarray(t.state.step)))
        t.close()
    l0, t0, s0 = losses[0]
    for chunk in (2, 3):
        lc, tc, sc = losses[chunk]
        assert s0 == sc == len(l0) == len(lc)
        assert t0 == tc
        np.testing.assert_array_equal(l0, lc)


def test_goodput_record_carries_step_breakdown(run_dir):
    """The h2d-wait / dispatch / compute breakdown must ride the attempt's
    goodput record (how overlap health reaches GOODPUT.json)."""
    import json

    tmp_path, version, _, _ = run_dir
    record = json.loads(
        (tmp_path / f"version-{version}" / "goodput.jsonl")
        .read_text().splitlines()[0]
    )
    breakdown = record["step_breakdown"]
    assert set(breakdown) == {"h2d_wait_s", "dispatch_s", "compute_s", "chunks"}
    assert breakdown["chunks"] >= 2  # one per epoch at the default chunk
    assert breakdown["dispatch_s"] >= 0.0


def test_resume_continues(run_dir, tmp_path):
    src_tmp, version, _, trainer = run_dir
    last = src_tmp / f"version-{version}" / "last.ckpt"
    hp = _hparams(tmp_path, extra=["--resume", str(last), "--epoch", "3"])
    t2 = Trainer(hp, model=TinyNet(num_classes=100))
    assert t2.start_epoch == 2  # resumes after the 2 completed epochs
    assert int(np.asarray(t2.state.step)) == int(np.asarray(trainer.state.step))
    t2.fit()  # one more epoch runs without error
    t2.close()


def test_batch_not_divisible_raises(tmp_path):
    hp = _hparams(tmp_path)
    hp.batch_size = 60  # not divisible by 8-device data axis
    with pytest.raises(ValueError, match="not divisible"):
        Trainer(hp, model=TinyNet(num_classes=100))
