"""Elastic-pool e2e child: one rendered rank of a supervised CPU fleet.

Launched by ``tests/test_fleet_pool.py`` through the real CLI
(``--supervise --fleet-hosts 2``), which routes ``run_supervised`` to the
:class:`FleetSupervisor`.  The fleet re-renders ``--world-size``/
``--rank``/``--dist-url`` per attempt and spawns this same script once per
rank:

- **rank 0** runs a real ``Trainer`` attempt (TinyNet, device data mode) —
  checkpoints, preemption drain on SIGTERM, the full product path;
- **rank > 0** is an **emulated host** (the ``tests/fleet_worker.py``
  pattern): a real process with a real pid whose interface to the
  supervisor is exactly a real host's — per-process event files with
  heartbeats in the shared version dir, ``EXIT_PREEMPTED`` on SIGTERM
  (the drain), death by whatever signal the test sends.  It exits 0 on
  its own when rank 0's ``run_end`` lands, so a clean attempt completes
  without supervisor intervention.

Why emulated: the pinned CI jax cannot run multi-process collectives on
the CPU backend (``Multiprocess computations aren't implemented``, see
tests/test_multihost.py — slow-marked for real TPU pods), so rank 0
deliberately skips ``init_distributed`` here.  Every SUPERVISOR-side code
path — spawn set, pidfiles, kill detection, pool transitions, world
re-render, deliberate drain, resize events, watcher host set — consumes
processes and files, never collectives, and is exercised for real.  The
production entry (``src/tpu_jax/main.py``) does call ``init_distributed``
with the rendered flags.
"""

import os
import signal
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize may pin the TPU plugin

import flax.linen as lnn
import jax.numpy as jnp


class TinyNet(lnn.Module):
    """Conv+BN+dense classifier sharing the zoo interface (duplicated from
    tests/test_train.py so the worker is standalone)."""

    num_classes: int = 100
    dtype: jnp.dtype = jnp.float32

    @lnn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = lnn.Conv(8, (3, 3), strides=2, use_bias=False, dtype=self.dtype)(x)
        x = lnn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = lnn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        return lnn.Dense(self.num_classes, dtype=self.dtype)(x).astype(jnp.float32)


def emulate_host(hp, rank: int) -> int:
    """A non-zero rank at the file level: bind a per-process event bus into
    the run's version dir, heartbeat on the configured cadence, exit 0 when
    rank 0 finishes (its ``run_end``), 75 on SIGTERM (the drain a real host
    would run), or by whatever signal kills the process.

    Chaos injection (resilience/faults.py ``EMU_SLOW_DISPATCH_ENV``): when
    the env var is set, this host reports a persistently slowed
    ``step/dispatch_s`` sketch — the straggler a ``--policy`` drain rule
    must remove.  Emission waits for rank 0's first verified checkpoint so
    the policy-driven drain always lands on a resumable run."""
    from distributed_training_comparison_tpu import obs
    from distributed_training_comparison_tpu.resilience import (
        EXIT_PREEMPTED,
        read_manifest,
    )
    from distributed_training_comparison_tpu.resilience.faults import (
        EMU_SLOW_DISPATCH_ENV,
    )

    drained = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *_: drained.__setitem__("flag", True))

    root = Path(hp.ckpt_path)
    deadline = time.monotonic() + 300.0
    vdir = None
    while vdir is None and time.monotonic() < deadline:
        if drained["flag"]:
            return EXIT_PREEMPTED
        dirs = sorted(root.glob("version-*"))
        if dirs:
            vdir = dirs[-1]
        else:
            time.sleep(0.05)
    if vdir is None:
        return 1
    bus = obs.EventBus(
        run_id=os.environ.get(obs.RUN_ID_ENV) or obs.new_run_id(),
        attempt=int(os.environ.get(obs.ATTEMPT_ENV, "0") or 0),
        process_index=rank,
    )
    bus.bind_dir(vdir)
    hb = obs.HeartbeatEmitter(bus, every_s=getattr(hp, "heartbeat_secs", 0.2))
    slow_dispatch_s = float(os.environ.get(EMU_SLOW_DISPATCH_ENV, "0") or 0)
    reg = obs.MetricRegistry(flush_steps=1) if slow_dispatch_s > 0 else None
    straggling = False
    last_straggle = 0.0
    step = 0
    events = vdir / "events.jsonl"  # rank 0's file: run_end says we're done
    try:
        # start at the current tail: a previous attempt's run_end/abort in
        # the same (auto-resumed) version dir is not OUR attempt's verdict
        offset = events.stat().st_size
    except OSError:
        offset = 0
    rc = 1  # timeout without a verdict is a failure
    while time.monotonic() < deadline:
        if drained["flag"]:
            rc = EXIT_PREEMPTED
            break
        hb.beat(epoch=0, step=step)
        step += 1
        if reg is not None:
            if not straggling:
                # hold the injection until rank 0 has a resumable state
                straggling = (
                    read_manifest(vdir / "last.ckpt") is not None
                )
            if straggling and time.monotonic() - last_straggle > 0.3:
                last_straggle = time.monotonic()
                # one flushed window of pathologically slow dispatch: the
                # per-process p95 alert on this source fires after for=N
                # windows, and the policy names THIS host for the drain
                reg.histogram("step/dispatch_s").record_many(
                    [slow_dispatch_s] * 4
                )
                reg.note_steps(4)
                reg.flush(bus, epoch=0, step=step)
        try:
            with open(events, "rb") as f:
                f.seek(offset)
                chunk = f.read().decode("utf-8", "replace")
                offset += len(chunk.encode("utf-8"))
        except OSError:
            chunk = ""
        if '"kind": "run_end"' in chunk:
            rc = 0
            break
        if '"kind": "abort"' in chunk:
            rc = 1
            break
        time.sleep(0.05)
    bus.close()
    print(f"RESULT emulated host rank={rank} rc={rc}", flush=True)
    return rc


def main(argv) -> int:
    from distributed_training_comparison_tpu.config import load_config
    from distributed_training_comparison_tpu.resilience import (
        EXIT_PREEMPTED,
        Preempted,
    )
    from distributed_training_comparison_tpu.utils import (
        enable_persistent_compilation_cache,
    )

    hp = load_config("tpu", argv)
    if getattr(hp, "supervise", False):
        from distributed_training_comparison_tpu.resilience.supervisor import (
            run_supervised,
        )

        return int(run_supervised(hp, argv)["exit_code"])

    if hp.rank > 0:
        return emulate_host(hp, hp.rank)

    enable_persistent_compilation_cache()
    from distributed_training_comparison_tpu.train import Trainer

    trainer = Trainer(hp, model=TinyNet(num_classes=100))
    try:
        version = trainer.fit()
    except Preempted as e:
        print(
            f"RESULT preempted=1 rank=0 epoch={e.epoch} "
            f"rendered_world={hp.world_size}",
            flush=True,
        )
        return EXIT_PREEMPTED
    finally:
        trainer.close()
    print(
        f"RESULT preempted=0 rank=0 rendered_world={hp.world_size} "
        f"version={version}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
