"""Overlapped-execution tests: DevicePrefetcher semantics (order, depth
bound, cancellation, exception propagation), donated runners, the chunked
device-mode runner's bit-identity contract, the step-time meter, and the
pipelined checkpoint read+hash.

The perf-marked tests are the overlap microbenchmarks: they measure the
mechanism (staging latency hidden behind consumer work) with deterministic
sleep-based stages, device-free — slow-marked so tier-1 skips them.
"""

import hashlib
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_comparison_tpu.data import (
    DeviceDataset,
    DevicePrefetcher,
    HostLoader,
    PrefetchLoader,
    chunked_batches,
    synthetic_dataset,
)
from distributed_training_comparison_tpu.parallel import (
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
from distributed_training_comparison_tpu.resilience import (
    atomic_write_bytes,
    read_and_hash,
    verify_checkpoint,
    write_manifest,
)
from distributed_training_comparison_tpu.train import (
    configure_optimizers,
    create_train_state,
    make_chunk_runner,
    make_device_chunk_runner,
    make_epoch_runner,
)
from distributed_training_comparison_tpu.utils import StepTimeMeter

from test_train import HP, TinyNet


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(backend="ddp")


@pytest.fixture(scope="module")
def tiny_data():
    x, y = synthetic_dataset(256, num_classes=10, seed=0)
    return jnp.asarray(x), jnp.asarray(y)


def _fresh_state(mesh):
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(TinyNet(), jax.random.key(0), tx)
    return jax.device_put(state, replicated_sharding(mesh))


# -------------------------------------------- device-mode chunked runner


def test_device_chunk_runner_bit_identical_to_monolithic(mesh, tiny_data):
    """The chunked device runner must reproduce the monolithic epoch
    runner's trajectory EXACTLY for any chunk size (the permutation and the
    per-step key split are recomputed and sliced, never re-derived)."""
    x, y = tiny_data
    bs = 32
    steps = len(x) // bs  # 8
    key = jax.random.key(7)

    def run_monolithic():
        runner = make_epoch_runner(mesh, bs)
        state = _fresh_state(mesh)
        losses = []
        for e in range(2):
            state, stacked = runner(state, x, y, key, jnp.asarray(e))
            losses.append(np.asarray(stacked["loss"]))
        return np.concatenate(losses), jax.device_get(state.params)

    def run_chunked(chunk):
        runner = make_device_chunk_runner(mesh, bs, chunk)
        rem = steps % chunk
        rem_runner = (
            make_device_chunk_runner(mesh, bs, rem) if rem else None
        )
        state = _fresh_state(mesh)
        losses = []
        for e in range(2):
            start = 0
            while start < steps:
                take = min(chunk, steps - start)
                r = runner if take == chunk else rem_runner
                state, stacked = r(
                    state, x, y, key, jnp.asarray(e), jnp.asarray(start)
                )
                losses.append(np.asarray(stacked["loss"]))
                start += take
        return np.concatenate(losses), jax.device_get(state.params)

    ref_losses, ref_params = run_monolithic()
    assert len(ref_losses) == 2 * steps
    for chunk in (1, 3, 8):
        losses, params = run_chunked(chunk)
        np.testing.assert_array_equal(losses, ref_losses)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b), params, ref_params
        )


def test_device_chunk_runner_fault_indices_are_epoch_global(mesh, tiny_data):
    """The traced step-fault window indexes steps WITHIN the epoch, exactly
    like the monolithic fault runner — a fault on steps [2, 5) must hit the
    same batches regardless of how the epoch is chunked."""
    x, y = tiny_data
    bs, steps = 32, 8
    key = jax.random.key(7)
    fault = (64.0, 2, 5)

    runner = make_epoch_runner(mesh, bs, fault_injection=True)
    state, stacked = runner(
        _fresh_state(mesh), x, y, key, jnp.asarray(0), fault
    )
    ref = np.asarray(stacked["loss"])

    crunner = make_device_chunk_runner(mesh, bs, 3, fault_injection=True)
    rrunner = make_device_chunk_runner(mesh, bs, 2, fault_injection=True)
    state = _fresh_state(mesh)
    losses = []
    for start, r in ((0, crunner), (3, crunner), (6, rrunner)):
        state, stacked = r(
            state, x, y, key, jnp.asarray(0), jnp.asarray(start), fault
        )
        losses.append(np.asarray(stacked["loss"]))
    np.testing.assert_array_equal(np.concatenate(losses), ref)


def test_donated_runner_consumes_input_state(mesh, tiny_data):
    """Donation must actually take effect: the input state's buffers are
    consumed by the dispatch (this is what eliminates the per-dispatch HBM
    copy), while donate=False preserves them — the contract the trainer's
    writer-snapshot logic is built on."""
    x, y = tiny_data
    key = jax.random.key(3)
    cx = jnp.stack([x[:16], x[16:32]])  # (K=2, B=16, ...)
    cy = jnp.stack([y[:16], y[16:32]])

    donating = make_chunk_runner(mesh, augment=False)  # donate default True
    state = _fresh_state(mesh)
    leaf_before = jax.tree_util.tree_leaves(state.params)[0]
    new_state, _ = donating(state, cx, cy, key, jnp.asarray(0))
    jax.block_until_ready(new_state)
    assert leaf_before.is_deleted()

    keeping = make_chunk_runner(mesh, augment=False, donate=False)
    state = _fresh_state(mesh)
    leaf_before = jax.tree_util.tree_leaves(state.params)[0]
    new_state, _ = keeping(
        state, jnp.stack([x[:16], x[16:32]]), jnp.stack([y[:16], y[16:32]]),
        key, jnp.asarray(0),
    )
    jax.block_until_ready(new_state)
    assert not leaf_before.is_deleted()


def test_donated_cache_write_bar_blocks_only_barred_compiles():
    """Donated executables must never land in the persistent compile cache:
    on this jax's CPU backend a warm process deserializing one segfaults or
    silently corrupts the scanned carry (the bug _compat.
    donated_cache_write_barred / step._donated_jit exist for).  Normal
    programs keep caching — the guard must not disable the cache wholesale.

    Observes the LIVE cache dir (conftest's — the cache singleton latches
    its directory at first use, so redirecting the config mid-process is a
    no-op: exactly why the fix had to bar the WRITE, not move the dir) and
    identifies its own entries by uniquely-named probe functions, so a
    concurrent test process sharing the cache cannot race the assertion.
    """
    from pathlib import Path

    from distributed_training_comparison_tpu.train.step import _donated_jit

    cache_dir = Path(jax.config.jax_compilation_cache_dir)
    min_secs = jax.config.jax_persistent_cache_min_compile_time_secs

    def named_entries(token):
        if not cache_dir.exists():
            return set()
        return {p for p in cache_dir.rglob("*") if token in p.name}

    def overlap_cache_probe_barred(s, xs):
        return jax.lax.scan(lambda c, x: (c + x.sum(), x.mean()), s, xs)

    def overlap_cache_probe_open(s, xs):
        return jax.lax.scan(lambda c, x: (c + x.max(), x.min()), s, xs)

    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        barred = _donated_jit(overlap_cache_probe_barred, donate_argnums=(0,))
        out = barred(jnp.ones((32, 32)), jnp.ones((4, 16)))
        jax.block_until_ready(out)
        assert named_entries("overlap_cache_probe_barred") == set()

        open_jit = jax.jit(overlap_cache_probe_open)
        out = open_jit(jnp.ones((32, 32)), jnp.ones((4, 16)))
        jax.block_until_ready(out)
        assert named_entries("overlap_cache_probe_open")  # cache still works
    finally:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_secs
        )


# ------------------------------------------------------- chunked_batches


def test_chunked_batches_chunks_and_remainder():
    src = iter([(np.full(2, i), np.full(2, i)) for i in range(7)])
    out = list(chunked_batches(src, 7, 3))
    assert [(s, k) for s, k, _ in out] == [(0, 3), (3, 3), (6, 1)]
    np.testing.assert_array_equal(out[0][2]["x"][1], np.full(2, 1))


def test_chunked_batches_tolerates_short_source():
    """A source that runs dry mid-epoch must yield its partial chunk and
    stop — never explode with the PEP-479 RuntimeError."""
    src = iter([(np.zeros(1), np.zeros(1))] * 5)
    out = list(chunked_batches(src, 12, 4))
    assert [(s, k) for s, k, _ in out] == [(0, 4), (4, 1)]


# ------------------------------------------------------ DevicePrefetcher


def _counted_source(n, counter, item_shape=4):
    for i in range(n):
        counter[0] += 1
        yield np.full(item_shape, i, np.float32), np.full(item_shape, i, np.int32)


def test_device_prefetcher_preserves_sequence():
    """The prefetcher must deliver exactly the synchronous chunker's
    sequence — same starts, same takes, same stacked contents."""
    a, b = [0], [0]
    sync = list(chunked_batches(_counted_source(10, a), 10, 3))
    pf = DevicePrefetcher(
        _counted_source(10, b), 10, 3, place=lambda x: x, depth=2
    )
    staged = list(pf)
    assert [(s, k) for s, k, _ in staged] == [(s, k) for s, k, _ in sync]
    for (_, _, sb), (_, _, pb) in zip(sync, staged):
        np.testing.assert_array_equal(sb["x"], pb["x"])
        np.testing.assert_array_equal(sb["y"], pb["y"])


def test_device_prefetcher_depth_bounds_runahead():
    """The producer must not run ahead unboundedly: at depth D and chunk K,
    at most (delivered + D + 1 in-assembly) chunks' worth of source batches
    may be consumed — this is the HBM cap."""
    counter = [0]
    pf = DevicePrefetcher(
        _counted_source(100, counter), 100, 2, place=lambda x: x, depth=2
    )
    try:
        next(pf)
        deadline = time.monotonic() + 2.0
        while counter[0] < 6 and time.monotonic() < deadline:
            time.sleep(0.01)  # let the producer fill the queue
        time.sleep(0.2)  # then prove it stops there
        # delivered 1 chunk + 2 staged + 1 in assembly = at most 4 chunks = 8
        assert counter[0] <= 8
        assert counter[0] >= 6  # and it DID stage ahead of the consumer
    finally:
        pf.close()


def test_device_prefetcher_close_joins_producer():
    counter = [0]
    pf = DevicePrefetcher(
        _counted_source(1000, counter), 1000, 2, place=lambda x: x, depth=2
    )
    next(pf)
    pf.close()
    assert not pf._thread.is_alive()
    pf.close()  # idempotent


def test_device_prefetcher_propagates_source_errors():
    def bad():
        yield np.zeros(2), np.zeros(2)
        yield np.zeros(2), np.zeros(2)
        raise RuntimeError("loader failed")

    pf = DevicePrefetcher(bad(), 10, 2, place=lambda x: x, depth=2)
    next(pf)  # first chunk (2 batches) is fine
    with pytest.raises(RuntimeError, match="loader failed"):
        next(pf)
    assert not pf._thread.is_alive()  # the error path also joined


def test_device_prefetcher_propagates_place_errors():
    """A failing device_put (the H2D analogue of an OOM) surfaces at the
    consuming next(), not as a hung iterator."""

    def explode(_):
        raise ValueError("device_put failed")

    pf = DevicePrefetcher(
        _counted_source(10, [0]), 10, 2, place=explode, depth=2
    )
    with pytest.raises(ValueError, match="device_put failed"):
        next(pf)


def test_prefetch_loader_close_joins_producer():
    x, y = synthetic_dataset(128, num_classes=10, seed=4)
    ds = DeviceDataset(x, y, num_classes=10)
    pre = PrefetchLoader(HostLoader(ds, 32, shuffle=False, seed=1), depth=2)
    it = iter(pre)
    next(it)
    pre.close()  # explicit abort API: signal + drain + JOIN
    assert pre._thread is None
    # a fresh epoch after close works
    assert len(list(pre)) == len(pre)


# ---------------------------------------------------------- StepTimeMeter


def test_step_time_meter_phases_and_merge():
    m = StepTimeMeter()
    with m.phase("h2d_wait"):
        time.sleep(0.01)
    m.add("dispatch", 0.5)
    m.note_chunk()
    s = m.summary()
    assert s["h2d_wait_s"] >= 0.009 and s["dispatch_s"] == 0.5
    assert s["compute_s"] == 0.0 and s["chunks"] == 1

    total = StepTimeMeter()
    total.merge(m)
    total.merge(m)
    assert total.summary()["dispatch_s"] == 1.0
    assert total.chunks == 2
    m.reset()
    assert m.summary()["dispatch_s"] == 0.0


# ------------------------------------------------- pipelined read + hash


def test_read_and_hash_matches_single_pass(tmp_path):
    data = np.random.default_rng(0).bytes(100_000)
    path = tmp_path / "blob.ckpt"
    path.write_bytes(data)
    # the small-file fast path (plain read-then-hash)
    got, digest = read_and_hash(path)
    assert got == data and digest == hashlib.sha256(data).hexdigest()
    # the pipelined path, forced through many small chunks
    got, digest = read_and_hash(path, chunk_bytes=4096, pipeline_min_bytes=0)
    assert got == data
    assert digest == hashlib.sha256(data).hexdigest()
    # ragged tail: size not a chunk multiple
    got, digest = read_and_hash(path, chunk_bytes=4097, pipeline_min_bytes=0)
    assert got == data and digest == hashlib.sha256(data).hexdigest()
    # empty file edge (pipelined)
    (tmp_path / "empty").write_bytes(b"")
    got, digest = read_and_hash(tmp_path / "empty", pipeline_min_bytes=0)
    assert got == b"" and digest == hashlib.sha256(b"").hexdigest()


def test_read_and_hash_raises_reader_errors(tmp_path, monkeypatch):
    with pytest.raises(OSError):
        read_and_hash(tmp_path / "missing.ckpt")
    # pipelined reader: a file that shrinks below its stat size mid-read
    # must raise at the consumer, never hand back silently-short bytes
    import pathlib
    import types

    import distributed_training_comparison_tpu.resilience.ckpt_io as cio

    path = tmp_path / "shrinking.ckpt"
    path.write_bytes(b"x" * 10_000)

    class LyingPath(pathlib.PosixPath):
        """stat() overstates the size, as if the file shrank after stat."""

        def stat(self, **kw):
            real = super().stat(**kw)
            return types.SimpleNamespace(st_size=real.st_size * 2)

    monkeypatch.setattr(cio, "Path", LyingPath)
    with pytest.raises(OSError, match="truncated"):
        read_and_hash(path, chunk_bytes=4096, pipeline_min_bytes=0)


def test_verify_checkpoint_precomputed_digest(tmp_path):
    data = b"payload" * 1000
    path = tmp_path / "blob.ckpt"
    atomic_write_bytes(path, data)
    write_manifest(path, data, meta={"step": 1})
    got, digest = read_and_hash(path)
    ok, reason = verify_checkpoint(path, data=got, digest=digest)
    assert ok, reason
    # a wrong precomputed digest must fail verification (the digest is
    # trusted in place of re-hashing, so it must actually be checked)
    ok, reason = verify_checkpoint(
        path, data=got, digest=hashlib.sha256(b"other").hexdigest()
    )
    assert not ok and "checksum" in reason
    # no data at all: verify pays its own (pipelined) read
    ok, reason = verify_checkpoint(path)
    assert ok, reason


# --------------------------------------------------- perf microbenchmarks


@pytest.mark.slow
@pytest.mark.perf
def test_prefetcher_hides_staging_latency():
    """The mechanism microbenchmark: with staging and consumption both
    taking ~T per chunk (sleep-based — deterministic, device-free), the
    synchronous pipeline costs ~2T per chunk while the prefetched one
    approaches T: staging hides behind the consumer."""
    chunks, stage_s, consume_s = 12, 0.02, 0.02

    def slow_source():
        for i in range(chunks):
            time.sleep(stage_s)
            yield np.full(2, i), np.full(2, i)

    def consume(chunk_iter):
        t0 = time.monotonic()
        for _ in chunk_iter:
            time.sleep(consume_s)
        return time.monotonic() - t0

    sync_wall = consume(chunked_batches(slow_source(), chunks, 1))
    pf = DevicePrefetcher(slow_source(), chunks, 1, place=lambda x: x, depth=2)
    try:
        overlap_wall = consume(pf)
    finally:
        pf.close()
    # perfect overlap would be ~0.5x; require a solid 0.75x with margin
    # for scheduler noise on a loaded CI host
    assert overlap_wall < 0.75 * sync_wall, (overlap_wall, sync_wall)


@pytest.mark.slow
@pytest.mark.perf
def test_read_and_hash_pipeline_correct_at_scale(tmp_path):
    """The pipelined path at a realistic chunk count (32 MB through 8 MB
    chunks, forced past the small-file threshold) must agree exactly with
    the one-shot read-then-hash.  Timing ratios are deliberately NOT
    asserted here: on a page-cached CI file the read is a memcpy the hash
    cannot hide behind — which is exactly why small files take the serial
    path in production (PIPELINE_MIN_BYTES); the overlap's win condition is
    slow storage, not a warm page cache."""
    data = np.random.default_rng(1).bytes(32 << 20)
    path = tmp_path / "payload.bin"
    path.write_bytes(data)
    got, digest = read_and_hash(path, pipeline_min_bytes=0)
    assert got == data
    assert digest == hashlib.sha256(data).hexdigest()
