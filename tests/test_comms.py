"""Comms-layer tests (parallel/comms.py): ZeRO-style sharded weight
updates and compressed gradient sync, pinned against the unsharded fp32
baseline across every runner variant, plus the satellites that rode the
same PR — the shard_map per-device desync reduce and the run_report
--compute drain fold.

Numerical contract (the tiers the README documents):

- ``--shard-optim`` alone is the SAME arithmetic at a different layout:
  final params match the baseline to float reassociation (~1 ulp —
  asserted at 1e-5).
- ``--grad-comms fp16`` with error feedback tracks the fp32 trajectory to
  half-precision rounding (asserted at 1e-3).
- ``--grad-comms int8`` with error feedback keeps the LOSS trajectory
  within 1e-2 of fp32 — the error-feedback residual re-injects what the
  8-bit wire drops, so quantization noise dithers instead of biasing.
"""

import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
import run_report  # noqa: E402

from distributed_training_comparison_tpu.config import load_config
from distributed_training_comparison_tpu.data import synthetic_dataset
from distributed_training_comparison_tpu.health import (
    check_partial_desync,
    make_partial_fingerprint_fn,
    partial_fingerprints,
)
from distributed_training_comparison_tpu.obs import CompileMonitor, MetricRegistry
from distributed_training_comparison_tpu.parallel import (
    Comms,
    make_compressed_allreduce,
    make_mesh,
    opt_state_bytes,
    quantize_tree,
    replicated_sharding,
    state_shardings,
    zero_opt_shardings,
    zero_partition_spec,
)
from distributed_training_comparison_tpu.parallel.sharding import place_tree
from distributed_training_comparison_tpu.train import (
    Trainer,
    configure_optimizers,
    create_train_state,
    make_chunk_runner,
    make_device_chunk_runner,
    make_epoch_runner,
)

from test_train import HP, TinyNet


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(backend="ddp")  # (8, 1)


@pytest.fixture(scope="module")
def dp_tp_mesh():
    return make_mesh(model_parallel=2, backend="ddp")  # (4, 2)


@pytest.fixture(scope="module")
def tiny_data():
    x, y = synthetic_dataset(256, num_classes=10, seed=0)
    return jnp.asarray(x), jnp.asarray(y)


def _fresh_state(mesh):
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(TinyNet(), jax.random.key(0), tx)
    return jax.device_put(state, replicated_sharding(mesh))


def _has_data(spec) -> bool:
    """True when a PartitionSpec assigns any dimension to the data axis."""
    for entry in tuple(spec):
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        if "data" in names:
            return True
    return False


def _prepared(mesh, comms):
    """State + sharding tree laid out the way the Trainer wires a comms
    run: residual attached under compression, opt state ZeRO-sharded
    under shard_optim."""
    state = _fresh_state(mesh)
    sh = state_shardings(mesh, state)
    if comms is not None and comms.compressing:
        state = state.replace(comms_residual=comms.residual_init(state.params))
        sh = sh.replace(comms_residual=sh.params)
    if comms is not None and comms.shard_optim:
        sh = sh.replace(
            opt_state=zero_opt_shardings(mesh, state.opt_state, sh.opt_state)
        )
    return place_tree(state, sh), sh


# ------------------------------------------------------------ layout rules


def test_zero_partition_spec_rules():
    # largest free divisible dim takes the data axis
    assert zero_partition_spec((64, 32), None, 8) == P("data", None)
    assert zero_partition_spec((3, 3, 3, 8), None, 8) == P(
        None, None, None, "data"
    )
    # occupied dims are skipped; the layout composes with TP
    assert zero_partition_spec((64, 32), P(None, "model"), 8) == P(
        "data", "model"
    )
    # no divisible free dim → base layout unchanged
    assert zero_partition_spec((10,), None, 8) == P(None)
    assert zero_partition_spec((), None, 8) == P()
    # degenerate data axis → unchanged
    assert zero_partition_spec((64, 32), None, 1) == P(None, None)
    # already data-sharded → never double-assigned
    assert zero_partition_spec((64, 32), P("data"), 8) == P("data", None)


def test_zero_opt_shardings_shard_momentum_keep_scalars(mesh):
    state = _fresh_state(mesh)
    base = state_shardings(mesh, state)
    zsh = zero_opt_shardings(mesh, state.opt_state, base.opt_state)
    specs = [
        (np.shape(leaf), sh.spec)
        for leaf, sh in zip(
            jax.tree_util.tree_leaves(state.opt_state),
            jax.tree_util.tree_leaves(zsh),
        )
    ]
    assert any(
        _has_data(s) for shape, s in specs if shape != ()
    ), "no momentum leaf took the data axis"
    assert all(not _has_data(s) for shape, s in specs if shape == ())
    total, per_device = opt_state_bytes(state.opt_state, zsh)
    assert per_device < total  # the footprint claim, host-side


# --------------------------------------------------------- wire primitives


def test_quantize_tree_error_feedback_identity():
    key = jax.random.key(1)
    tree = {
        "w": jax.random.normal(key, (32, 16)) * 3.0,
        "b": jnp.zeros((7,)),
        "n": jnp.arange(4, dtype=jnp.int32),  # non-float passthrough
    }
    same, deq = quantize_tree(tree, "fp32")
    assert same is tree and deq(same) is same

    amax = float(jnp.max(jnp.abs(tree["w"])))
    for mode, dtype, bound in (
        ("fp16", jnp.float16, amax * 2**-10),  # half-precision ulp tier
        ("int8", jnp.int8, amax / 127),  # one quantization level
    ):
        wire, deq = quantize_tree(tree, mode)
        assert wire["w"].dtype == dtype
        assert wire["n"].dtype == jnp.int32  # untouched
        back = deq(wire)
        assert back["w"].dtype == jnp.float32
        err = jnp.max(jnp.abs(back["w"] - tree["w"]))
        assert float(err) <= bound
        # the EF identity: residual is exactly what the wire dropped
        residual = jax.tree_util.tree_map(jnp.subtract, tree["w"], back["w"])
        np.testing.assert_array_equal(
            np.asarray(residual), np.asarray(tree["w"]) - np.asarray(back["w"])
        )

    with pytest.raises(ValueError, match="grad-comms mode"):
        quantize_tree(tree, "fp8")


def test_fp16_wire_saturates_instead_of_overflowing():
    """A FINITE fp32 gradient past fp16's max (65504) must clip on the
    wire, never overflow to inf: the numerics guard checks the RAW
    pre-compression grads, so an inf born on the wire would dequantize
    into the update and poison params PAST the guard.  With error
    feedback the clipped excess lands in the residual (finite) and
    re-injects next step."""
    g = {"w": jnp.asarray([1e5, -3e5, 1.0], jnp.float32)}  # finite, >65504
    wire, deq = quantize_tree(g, "fp16")
    assert bool(jnp.isfinite(wire["w"]).all())
    back = deq(wire)
    np.testing.assert_allclose(
        np.asarray(back["w"]), [65504.0, -65504.0, 1.0], rtol=1e-3
    )
    residual = jax.tree_util.tree_map(jnp.subtract, g, back)
    assert bool(jnp.isfinite(residual["w"]).all())


def test_compressed_allreduce_wire_modes(mesh):
    n = mesh.shape["data"]
    x = jax.random.normal(jax.random.key(0), (n, 16, 8)) * 2.0
    exact = np.asarray(x).mean(0)
    for mode, tol in (("fp32", 1e-6), ("fp16", 5e-3), ("int8", 5e-2)):
        out = make_compressed_allreduce(mesh, mode)({"g": x})["g"]
        np.testing.assert_allclose(np.asarray(out), exact, atol=tol)
    # sum semantics
    out = make_compressed_allreduce(mesh, "fp32", mean=False)({"g": x})["g"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0), atol=1e-5)
    with pytest.raises(ValueError, match="grad-comms mode"):
        make_compressed_allreduce(mesh, "fp8")


# ------------------------------------------- runner-level numerical pinning


def _run_epochs(mesh, data, comms, epochs=2, runner_kind="epoch"):
    x, y = data
    bs = 32
    steps = len(x) // bs
    key = jax.random.key(7)
    state, sh = _prepared(mesh, comms)
    losses = []
    if runner_kind == "epoch":
        runner = make_epoch_runner(
            mesh, bs, state_sharding=sh, comms=comms, donate=False
        )
        for e in range(epochs):
            state, stacked = runner(state, x, y, key, jnp.asarray(e))
            losses.append(np.asarray(stacked["loss"]))
    elif runner_kind == "device_chunk":
        runner = make_device_chunk_runner(
            mesh, bs, 3, state_sharding=sh, comms=comms, donate=False
        )
        rem = make_device_chunk_runner(
            mesh, bs, steps % 3, state_sharding=sh, comms=comms, donate=False
        )
        for e in range(epochs):
            start = 0
            while start < steps:
                take = min(3, steps - start)
                r = runner if take == 3 else rem
                state, stacked = r(
                    state, x, y, key, jnp.asarray(e), jnp.asarray(start)
                )
                losses.append(np.asarray(stacked["loss"]))
                start += take
    elif runner_kind in ("chunk", "chunk_donated"):
        donate = runner_kind == "chunk_donated"
        runner = make_chunk_runner(
            mesh, state_sharding=sh, comms=comms, donate=donate
        )
        for e in range(epochs):
            epoch_key = jax.random.fold_in(key, e)
            cx = jnp.stack([x[i * bs:(i + 1) * bs] for i in range(steps)])
            cy = jnp.stack([y[i * bs:(i + 1) * bs] for i in range(steps)])
            state, stacked = runner(state, cx, cy, epoch_key, jnp.asarray(0))
            losses.append(np.asarray(stacked["loss"]))
    return np.concatenate(losses), jax.device_get(state.params), state


@pytest.mark.parametrize(
    "runner_kind", ["epoch", "device_chunk", "chunk", "chunk_donated"]
)
def test_sharded_update_matches_unsharded(mesh, tiny_data, runner_kind):
    """--shard-optim is the same arithmetic at a different layout: every
    runner variant (monolithic epoch, device-chunked, host-chunked,
    donated) must land on the baseline's params to float reassociation."""
    base_l, base_p, _ = _run_epochs(mesh, tiny_data, None, runner_kind=runner_kind)
    comms = Comms(mesh, shard_optim=True)
    l, p, state = _run_epochs(mesh, tiny_data, comms, runner_kind=runner_kind)
    np.testing.assert_allclose(l, base_l, atol=1e-5, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5),
        p, base_p,
    )
    # the layout is real: the momentum trace is carried data-sharded
    specs = [
        getattr(leaf.sharding, "spec", P())
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "sharding") and np.ndim(leaf) > 0
    ]
    assert any(
        _has_data(s) for s in specs
    ), f"no opt-state leaf carried data-sharded: {specs}"


def test_fp16_error_feedback_tracks_fp32(mesh, tiny_data):
    base_l, base_p, _ = _run_epochs(mesh, tiny_data, None, epochs=3)
    comms = Comms(mesh, grad_comms="fp16")
    l, p, state = _run_epochs(mesh, tiny_data, comms, epochs=3)
    np.testing.assert_allclose(l, base_l, atol=1e-3, rtol=1e-3)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-3), p, base_p
    )
    # the residual is genuinely carried (a zero residual would mean the
    # error-feedback path traced away)
    res_norm = sum(
        float(jnp.sum(jnp.abs(leaf)))
        for leaf in jax.tree_util.tree_leaves(state.comms_residual)
    )
    assert res_norm > 0.0


def test_int8_error_feedback_loss_trajectory(mesh, tiny_data):
    """int8 + error feedback keeps the loss trajectory within the
    documented 1e-2 of fp32; the sharded+compressed composition (the full
    --shard-optim --grad-comms int8 path) stays within the same tier."""
    base_l, _, _ = _run_epochs(mesh, tiny_data, None, epochs=3)
    l8, _, _ = _run_epochs(mesh, tiny_data, Comms(mesh, grad_comms="int8"), epochs=3)
    assert float(np.abs(l8 - base_l).max()) < 1e-2
    both, _, _ = _run_epochs(
        mesh, tiny_data,
        Comms(mesh, shard_optim=True, grad_comms="int8"), epochs=3,
    )
    assert float(np.abs(both - base_l).max()) < 1e-2


def test_comms_on_dp_tp_mesh(dp_tp_mesh, tiny_data):
    """The ZeRO layout composes with a nontrivial model axis: same
    numerics on a (4, 2) DP×TP mesh (TinyNet's params are replicated over
    'model', so the zero rule exercises the free-dimension path with the
    model axis present)."""
    base_l, base_p, _ = _run_epochs(dp_tp_mesh, tiny_data, None)
    comms = Comms(dp_tp_mesh, shard_optim=True, grad_comms="fp16")
    l, p, _ = _run_epochs(dp_tp_mesh, tiny_data, comms)
    np.testing.assert_allclose(l, base_l, atol=1e-3, rtol=1e-3)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-3), p, base_p
    )


def test_nonfinite_step_keeps_state_and_residual(mesh, tiny_data):
    """The numerics guard composes with the comms update: a NaN-scaled
    fault window skips the ENTIRE update — params, optimizer state, AND
    the error-feedback residual keep their old values."""
    x, y = tiny_data
    comms = Comms(mesh, shard_optim=True, grad_comms="int8")
    state, sh = _prepared(mesh, comms)
    runner = make_epoch_runner(
        mesh, 32, state_sharding=sh, comms=comms,
        fault_injection=True, donate=False,
    )
    before = jax.device_get(state.params)
    new_state, stacked = runner(
        state, x, y, jax.random.key(7), jnp.asarray(0),
        (float("nan"), 0, 8),  # every step of the epoch is non-finite
    )
    assert np.asarray(stacked["skipped"]).sum() == 8
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b),
        jax.device_get(new_state.params), before,
    )
    for leaf in jax.tree_util.tree_leaves(new_state.comms_residual):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)
    assert int(np.asarray(new_state.step)) == 0


def test_benign_path_fingerprint_unchanged(mesh, tiny_data):
    """Both flags off must trace the exact pre-comms update: an INACTIVE
    Comms and comms=None compile to the SAME executable fingerprint (the
    monitor dedups identical fingerprints — one record, two compiles),
    and the default TrainState flattens with no extra leaf."""
    x, y = tiny_data
    state = _fresh_state(mesh)
    n_leaves = len(jax.tree_util.tree_leaves(state))
    assert state.comms_residual is None
    assert len(jax.tree_util.tree_leaves(state.replace())) == n_leaves

    inactive = Comms(mesh)
    assert not inactive.active
    monitor = CompileMonitor(registry=MetricRegistry())
    for comms in (None, inactive):
        runner = make_epoch_runner(
            mesh, 32, comms=comms, donate=False, monitor=monitor
        )
        runner(_fresh_state(mesh), x, y, jax.random.key(7), jnp.asarray(0))
    ledger = monitor.ledger()
    assert len(ledger) == 1, [r["fingerprint"] for r in ledger]
    assert ledger[0]["compiles"] == 2


# --------------------------------------------------------------- e2e runs


def _hparams(tmp_path, extra=()):
    return load_config(
        "ddp",
        argv=[
            "--synthetic-data", "--limit-examples", "256",
            "--batch-size", "64", "--epoch", "2", "--eval-step", "100",
            "--lr", "0.05", "--no-progress", "--save-last-min-secs", "0",
            "--ckpt-path", str(tmp_path), *extra,
        ],
    )


def test_trainer_shard_optim_e2e_and_ckpt_roundtrip(tmp_path):
    """The full train stack under --shard-optim --grad-comms int8: the
    carried optimizer state is genuinely data-sharded, the comms/* gauges
    ride the metrics stream, run_start names the flags — and the
    checkpoint round-trips onto a run with BOTH flags off (the reshard
    step: host-pytree restore re-places the state, values unchanged)."""
    hp = _hparams(tmp_path, extra=["--shard-optim", "--grad-comms", "int8"])
    t = Trainer(hp, model=TinyNet(num_classes=100))
    # opt state carried sharded between dispatches
    specs = [
        leaf.sharding.spec
        for leaf in jax.tree_util.tree_leaves(t.state.opt_state)
        if np.ndim(leaf) > 0
    ]
    assert any(_has_data(s) for s in specs)
    assert t.state.comms_residual is not None
    version = t.fit()
    saved_state = jax.device_get(
        {"params": t.state.params, "opt_state": t.state.opt_state}
    )
    t.close()
    vdir = tmp_path / f"version-{version}"
    events = [
        json.loads(line)
        for line in (vdir / "events.jsonl").read_text().splitlines()
    ]
    run_start = next(e for e in events if e["kind"] == "run_start")
    assert run_start["payload"]["shard_optim"] is True
    assert run_start["payload"]["grad_comms"] == "int8"
    gauges = [
        m
        for e in events
        if e["kind"] == "metrics"
        for m in e["payload"]["metrics"]
        if m.startswith("comms/")
    ]
    assert {"comms/wire_bits", "comms/opt_state_bytes_per_device"} <= set(gauges)

    # restore across the sharding-mode change: both flags off
    hp2 = _hparams(
        tmp_path / "plain", extra=["--resume", str(vdir / "last.ckpt")]
    )
    t2 = Trainer(hp2, model=TinyNet(num_classes=100))
    assert t2.comms is None and t2.state.comms_residual is None
    restored = jax.device_get(
        {"params": t2.state.params, "opt_state": t2.state.opt_state}
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), restored, saved_state
    )
    assert t2._reshard["saved_shard_optim"] is True
    assert t2._reshard["shard_optim_changed"] is True
    t2.fit()  # one more epoch on the replicated layout runs clean
    t2.close()


# ------------------------------------------------- satellite: desync reduce


def test_partial_fingerprint_device_path_detects_single_bit_drift(dp_tp_mesh):
    """The compiled per-device reduce must be at least as sensitive as
    the host path it replaces: its wrapping-int32 bitcast checksum
    catches a SINGLE low-order-bit flip on one device of a 262k-element
    leaf — the case a float32 abs-sum would round away (and the reason
    the device path deliberately does not reuse the float
    param_fingerprint formula)."""
    mesh = dp_tp_mesh
    repl = NamedSharding(mesh, P())
    tp = NamedSharding(mesh, P(None, "model"))
    params = {
        "w": jax.device_put(
            jax.random.normal(jax.random.key(1), (64, 32)), tp
        ),
        "big": jax.device_put(
            jax.random.normal(jax.random.key(2), (1 << 18,)), repl
        ),
    }
    shardings = {"w": tp, "big": repl}
    fn = make_partial_fingerprint_fn(mesh, shardings)
    device = np.asarray(fn(params))
    assert device.shape == (4, 2, 1)  # (data, model, pipe)
    # in-sync replicas: every model column constant down the data axis
    assert not check_partial_desync(device)["mismatch"]
    # host path agrees on the in-sync verdict (different checksum, same
    # contract)
    assert not check_partial_desync(
        partial_fingerprints(params, mesh)
    )["mismatch"]
    # injected drift down a column is caught on the device matrix
    assert check_partial_desync(device, inject=True)["mismatch"]

    # real per-replica drift: ONE low bit flipped in one device's copy of
    # the "replicated" big leaf (constructed from per-device buffers, the
    # way an actual desync presents)
    base = np.asarray(jax.device_get(params["big"]), np.float32)
    drift = base.copy()
    drift.view(np.int32)[12345] ^= 1  # 1 ulp
    bufs = [
        jax.device_put(drift if i == 5 else base, d)
        for i, d in enumerate(mesh.devices.flat)
    ]
    params["big"] = jax.make_array_from_single_device_arrays(
        base.shape, repl, bufs
    )
    verdict = check_partial_desync(np.asarray(fn(params)))
    assert verdict["mismatch"], "single-bit replica drift went undetected"


# ---------------------------------------------- satellite: --compute drain


def _compile_event(name, fp, **payload):
    return {
        "v": 1, "run_id": "r", "attempt": 0, "process_index": 0,
        "t_wall": 1.0, "t_mono": 1.0, "kind": "compile",
        "payload": {
            "name": name, "fingerprint": fp, "compile_s": 0.5,
            "cache": "miss", "compiles_of_fingerprint": 1,
            "recompile_after_warmup": False, "platform": "tpu",
            "device_kind": "TPU v4", "devices": 4, "flops": 1e12,
            "peak_bytes": 2 << 30, **payload,
        },
    }


def _exec_flush(name, fp, count, total_s):
    reg = MetricRegistry()
    h = reg.histogram(f"exec/{name}:{fp[:8]}/dispatch_s")
    for _ in range(count):
        h.record(total_s / count)
    return {
        "v": 1, "run_id": "r", "attempt": 0, "process_index": 0,
        "t_wall": 2.0, "t_mono": 2.0, "kind": "metrics",
        "payload": {"metrics": reg.snapshot(reset=False)},
    }


def _metrics_flush(values: dict):
    reg = MetricRegistry()
    for name, total in values.items():
        reg.histogram(name).record(total)
    return {
        "v": 1, "run_id": "r", "attempt": 0, "process_index": 0,
        "t_wall": 3.0, "t_mono": 3.0, "kind": "metrics",
        "payload": {"metrics": reg.snapshot(reset=False)},
    }


def test_compute_summary_folds_compute_drain():
    """The epoch-final chunk drains inside the metrics fetch: its device
    time lands in step/compute_s, not a dispatch span.  --compute folds
    that span into the MFU denominator pro-rata, so measured MFU stops
    overcounting."""
    fp = "aabbccddeeff0011"
    events = [
        _compile_event("chunk_runner", fp),
        _exec_flush("chunk_runner", fp, count=10, total_s=10.0),
        _metrics_flush({"step/compute_s": 5.0}),
    ]
    comp = run_report.compute_summary(events)
    (row,) = comp["rows"]
    assert row["drain_s"] == pytest.approx(comp["totals"]["drain_s"], rel=1e-6)
    assert comp["totals"]["drain_s"] == pytest.approx(5.0, rel=0.05)
    span = row["dispatch_s"] + row["drain_s"]
    assert row["mfu"] == pytest.approx(
        1e12 * 10 / span / (275e12 * 4), rel=1e-6
    )
    assert "drain folded" in run_report.format_compute(comp)


# ------------------------------------------------- satellite: bench leg


@pytest.mark.slow
@pytest.mark.perf
def test_bench_comms_ledger(tmp_path):
    """The --comms bench leg end to end (two legs only — the committed
    BENCH_COMMS.json runs all five): the compile-event ledger must show
    the opt-state footprint sharding 1/N, and the capture must
    self-validate."""
    sys.path.insert(0, str(Path(__file__).parent.parent))
    import bench

    record = bench.bench_comms(
        out_path=str(tmp_path / "BENCH_COMMS.json"),
        legs=("base", "shard_optim"),
    )
    assert record["events_check_rc"] == 0
    ledger = record["ledger"]
    assert ledger["opt_state_shard_ratio"] <= 0.5  # ~1/N on a 4-way axis
    assert ledger["measured_saving_bytes"] > 0
    assert (
        ledger["update_bytes_shard_optim"] < ledger["update_bytes_base"]
    )
    assert record["loss_vs_base"]["shard_optim"] < 1e-4


# ----------------------------------------------------------- config flags


def test_config_comms_flags():
    hp = load_config("ddp", argv=["--shard-optim", "--grad-comms", "int8"])
    assert hp.shard_optim is True and hp.grad_comms == "int8"
    hp = load_config("ddp", argv=[])
    assert hp.shard_optim is False and hp.grad_comms == "fp32"
    with pytest.raises(SystemExit):
        load_config("ddp", argv=["--grad-comms", "fp8"])
