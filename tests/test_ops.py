"""Pallas flash-attention kernel vs the jnp reference (interpret mode).

The kernels are exercised through the Pallas interpreter so the exact
production code paths (fwd + both backward kernels, masking, padding,
causal block-skipping) run in CI on the CPU mesh.  Comparisons run under
``default_matmul_precision("highest")`` — this CPU backend's default
matmul precision is bf16-like, which would drown the parity signal.

On real TPU hardware the same checks hold at bf16 tolerance and run at
their design points in ``tests_tpu/``; measured v5e throughput lives in
the README's flash-attention table (reproduced by ``python bench.py``).
"""

import jax
import jax.numpy as jnp
import pytest

from distributed_training_comparison_tpu.ops import (
    attention,
    flash_attention,
    mha_reference,
)


def _rand_qkv(seed, sq, skv, d, dtype=jnp.float32, b=2, h=3):
    kq, kk, kv, kdo = jax.random.split(jax.random.key(seed), 4)
    return (
        jax.random.normal(kq, (b, h, sq, d), dtype),
        jax.random.normal(kk, (b, h, skv, d), dtype),
        jax.random.normal(kv, (b, h, skv, d), dtype),
        jax.random.normal(kdo, (b, h, sq, d), dtype),
    )


# fast gate keeps one non-causal + one causal representative; the padded /
# cross-attention variants run in the full suite
@pytest.mark.parametrize(
    "causal,sq,skv,d",
    [
        (False, 256, 256, 64),   # aligned
        pytest.param(False, 200, 200, 48, marks=pytest.mark.slow),   # seq and head-dim padding
        pytest.param(False, 128, 384, 64, marks=pytest.mark.slow),   # cross-attention (kv longer)
        pytest.param(False, 64, 500, 128, marks=pytest.mark.slow),   # both lengths padded, full-width head
        (True, 256, 256, 64),
        pytest.param(True, 200, 200, 48, marks=pytest.mark.slow),
        # multi-tile backward: padded 1024 > the 512 streamed tile, so the
        # causal diagonal gate, lo-based accumulator init, and cross-step
        # scratch accumulation actually execute (single-tile cases leave
        # them dead)
        pytest.param(True, 1024, 1024, 64, marks=pytest.mark.slow),
        pytest.param(True, 1000, 1000, 64, marks=pytest.mark.slow),
        pytest.param(False, 640, 1152, 64, marks=pytest.mark.slow),
    ],
)
def test_flash_matches_reference(causal, sq, skv, d):
    q, k, v, do = _rand_qkv(sq * 7 + d + causal, sq, skv, d)
    with jax.default_matmul_precision("highest"):
        out_f, vjp_f = jax.vjp(
            lambda q, k, v: flash_attention(q, k, v, causal=causal, interpret=True),
            q, k, v,
        )
        out_r, vjp_r = jax.vjp(
            lambda q, k, v: mha_reference(q, k, v, causal=causal), q, k, v
        )
        grads_f, grads_r = vjp_f(do), vjp_r(do)

    assert out_f.shape == (q.shape[0], q.shape[1], sq, d)
    assert float(jnp.max(jnp.abs(out_f - out_r))) < 2e-5
    for gf, gr, name in zip(grads_f, grads_r, "qkv"):
        assert float(jnp.max(jnp.abs(gf - gr))) < 5e-4, f"d{name} mismatch"


@pytest.mark.parametrize(
    "causal,sq,skv,d",
    [
        (False, 256, 256, 64),
        # multi-tile causal: diagonal gate + scratch carry across key steps
        pytest.param(True, 1024, 1024, 64, marks=pytest.mark.slow),
        # padded seq + head dim
        pytest.param(False, 200, 200, 48, marks=pytest.mark.slow),
        # cross-attention with kv padding
        pytest.param(False, 640, 1152, 64, marks=pytest.mark.slow),
    ],
)
def test_flash_tiled_forward_matches_reference(monkeypatch, causal, sq, skv, d):
    """The streamed-K/V forward (selected above _FWD_RESIDENT_KV_LIMIT) is
    numerically the same kernel contract as the resident-K/V one; force it
    by zeroing the limit and check outputs + grads against the reference."""
    import importlib

    A = importlib.import_module(
        "distributed_training_comparison_tpu.ops.attention"
    )
    monkeypatch.setattr(A, "_FWD_RESIDENT_KV_LIMIT", 0)
    q, k, v, do = _rand_qkv(sq * 3 + d + causal, sq, skv, d)
    with jax.default_matmul_precision("highest"):
        out_f, vjp_f = jax.vjp(
            lambda q, k, v: flash_attention(q, k, v, causal=causal, interpret=True),
            q, k, v,
        )
        out_r, vjp_r = jax.vjp(
            lambda q, k, v: mha_reference(q, k, v, causal=causal), q, k, v
        )
        grads_f, grads_r = vjp_f(do), vjp_r(do)
    assert float(jnp.max(jnp.abs(out_f - out_r))) < 2e-5
    for gf, gr, name in zip(grads_f, grads_r, "qkv"):
        assert float(jnp.max(jnp.abs(gf - gr))) < 5e-4, f"d{name} mismatch"


def test_flash_tiled_forward_fully_masked_tile(monkeypatch):
    """Explicit block_k much larger than the true key length pads past a
    whole 512-wide streamed tile, so a fully-masked stream tile is
    visited: its contribution must be exactly zero and the online-softmax
    scratch must carry through it unchanged."""
    import importlib

    A = importlib.import_module(
        "distributed_training_comparison_tpu.ops.attention"
    )
    monkeypatch.setattr(A, "_FWD_RESIDENT_KV_LIMIT", 0)
    q, k, v, _ = _rand_qkv(7, 256, 300, 64)
    with jax.default_matmul_precision("highest"):
        out = flash_attention(q, k, v, block_k=1024, interpret=True)
        base = mha_reference(q, k, v)
    assert float(jnp.max(jnp.abs(out - base))) < 2e-5


def test_flash_non_pow2_padded_length(monkeypatch):
    """Caller-chosen blocks can pad the sequence to a non-multiple of 128
    (block_q=64, sq=150 → padded 192).  The streamed tiles must still
    cover the whole padded length — a non-divisor tile makes the grid's
    floor division silently drop the tail block (rows beyond it would be
    garbage in the fwd output and dq, and tail keys would never
    contribute to dk/dv)."""
    import importlib

    A = importlib.import_module(
        "distributed_training_comparison_tpu.ops.attention"
    )
    monkeypatch.setattr(A, "_FWD_RESIDENT_KV_LIMIT", 0)  # tiled fwd too
    q, k, v, do = _rand_qkv(13, 150, 150, 64)
    with jax.default_matmul_precision("highest"):
        out_f, vjp_f = jax.vjp(
            lambda q, k, v: flash_attention(
                q, k, v, block_q=64, block_k=64, interpret=True
            ),
            q, k, v,
        )
        out_r, vjp_r = jax.vjp(lambda q, k, v: mha_reference(q, k, v), q, k, v)
        grads_f, grads_r = vjp_f(do), vjp_r(do)
    assert float(jnp.max(jnp.abs(out_f - out_r))) < 2e-5
    for gf, gr, name in zip(grads_f, grads_r, "qkv"):
        assert float(jnp.max(jnp.abs(gf - gr))) < 5e-4, f"d{name} mismatch"


def test_flash_streamed_causal_mask_free_interior(monkeypatch):
    """Streamed causal forward at S=4096 (forced via the resident limit):
    with the 2048-row query tile the grid has interior tiles fully below
    the diagonal — the causal mask-free branch of the streamed forward
    (``_mask_split``) — plus straddling and skipped tiles.  All three
    classes must agree with the reference."""
    import importlib

    A = importlib.import_module(
        "distributed_training_comparison_tpu.ops.attention"
    )
    monkeypatch.setattr(A, "_FWD_RESIDENT_KV_LIMIT", 0)
    q, k, v, _ = _rand_qkv(19, 4096, 4096, 64, b=1, h=1)
    with jax.default_matmul_precision("highest"):
        out = flash_attention(q, k, v, causal=True, interpret=True)
        base = mha_reference(q, k, v, causal=True)
    assert float(jnp.max(jnp.abs(out - base))) < 2e-5


def test_flash_causal_backward_mask_free_interior():
    """Causal fwd+bwd at S=1024: the backward's (512, 512) stream tiles
    give both dq and dk/dv grids tiles fully below the diagonal — the
    causal mask-free branch of both backward kernels — which smaller
    causal tests (S<=512, single-tile grids) never reach."""
    q, k, v, do = _rand_qkv(23, 1024, 1024, 64, b=1, h=2)
    with jax.default_matmul_precision("highest"):
        out_f, vjp_f = jax.vjp(
            lambda q, k, v: flash_attention(q, k, v, causal=True, interpret=True),
            q, k, v,
        )
        out_r, vjp_r = jax.vjp(
            lambda q, k, v: mha_reference(q, k, v, causal=True), q, k, v
        )
        grads_f, grads_r = vjp_f(do), vjp_r(do)
    assert float(jnp.max(jnp.abs(out_f - out_r))) < 2e-5
    for gf, gr, name in zip(grads_f, grads_r, "qkv"):
        assert float(jnp.max(jnp.abs(gf - gr))) < 5e-4, f"d{name} mismatch"


def test_flash_causal_key_blocks_past_query_padding():
    """Causal with caller blocks padding K/V far past the padded query
    length (s=129, block_q=64, block_k=1024): the dkv backward grid gets
    key blocks whose first intersecting query block lies beyond the grid
    (lo >= nq), so no compute step visits them — the kernel's i==0
    pre-write of zero output blocks (not stale scratch) is what flushes
    (ADVICE r4).  Gradients on the real rows must match the reference."""
    q, k, v, do = _rand_qkv(17, 129, 129, 64)
    with jax.default_matmul_precision("highest"):
        out_f, vjp_f = jax.vjp(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=64, block_k=1024, interpret=True
            ),
            q, k, v,
        )
        out_r, vjp_r = jax.vjp(
            lambda q, k, v: mha_reference(q, k, v, causal=True), q, k, v
        )
        grads_f, grads_r = vjp_f(do), vjp_r(do)
    assert float(jnp.max(jnp.abs(out_f - out_r))) < 2e-5
    for gf, gr, name in zip(grads_f, grads_r, "qkv"):
        assert float(jnp.max(jnp.abs(gf - gr))) < 5e-4, f"d{name} mismatch"


def test_flash_explicit_blocks():
    """Non-default block shapes (incl. block_k spanning the whole padded
    sequence, the measured-fastest TPU config) agree with the default."""
    q, k, v, _ = _rand_qkv(11, 256, 512, 64)
    with jax.default_matmul_precision("highest"):
        base = mha_reference(q, k, v)
        for bq, bk in [(128, 512), (256, 256), (128, 128)]:
            out = flash_attention(q, k, v, block_q=bq, block_k=bk, interpret=True)
            assert float(jnp.max(jnp.abs(out - base))) < 2e-5, (bq, bk)


def test_flash_causal_masks_future():
    """Perturbing future keys/values never changes causal output."""
    q, k, v, _ = _rand_qkv(3, 256, 256, 64)
    with jax.default_matmul_precision("highest"):
        out = flash_attention(q, k, v, causal=True, interpret=True)
        k2 = k.at[:, :, 200:, :].add(5.0)
        v2 = v.at[:, :, 200:, :].add(-3.0)
        out2 = flash_attention(q, k2, v2, causal=True, interpret=True)
    # rows < 200 attend only to keys ≤ row index < 200 → identical
    assert float(jnp.max(jnp.abs(out[:, :, :200] - out2[:, :, :200]))) == 0.0
    # last rows do see the perturbation
    assert float(jnp.max(jnp.abs(out[:, :, 200:] - out2[:, :, 200:]))) > 1e-3


def test_flash_causal_requires_square():
    q, k, v, _ = _rand_qkv(0, 128, 256, 64)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=True, interpret=True)


def test_attention_dispatcher():
    q, k, v, _ = _rand_qkv(5, 64, 64, 32)
    with jax.default_matmul_precision("highest"):
        # CPU backend → auto resolves to the reference implementation
        out_auto = attention(q, k, v)
        out_ref = attention(q, k, v, impl="reference")
    assert float(jnp.max(jnp.abs(out_auto - out_ref))) == 0.0
    with pytest.raises(ValueError, match="unknown attention impl"):
        attention(q, k, v, impl="nope")
    # near-miss sequence-parallel names must fail fast, not silently route
    for typo in ("ring_attn", "rings", "ulysses2"):
        with pytest.raises(ValueError, match="unknown attention impl"):
            attention(q, k, v, impl=typo)


def test_attention_pallas_off_tpu():
    """Explicit impl='pallas' off-TPU must fail with a clear message, not an
    opaque Mosaic lowering error — unless interpret=True is plumbed through
    (advisor r2)."""
    q, k, v, _ = _rand_qkv(6, 128, 128, 32)
    with pytest.raises(ValueError, match="requires a TPU backend"):
        attention(q, k, v, impl="pallas")
    with jax.default_matmul_precision("highest"):
        out = attention(q, k, v, impl="pallas", interpret=True)
        ref = attention(q, k, v, impl="reference")
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


@pytest.mark.parametrize("causal", [False, True])
def test_flash_lse_and_its_cotangent(causal):
    """return_lse parity AND the dlse backward path through the Pallas
    kernels: a loss that uses BOTH outputs must match reference autodiff —
    this is the path ring attention differentiates through."""
    q, k, v, do = _rand_qkv(21 + causal, 200, 200, 64)

    def loss(attn):
        def f(q, k, v):
            o, lse = attn(q, k, v)
            return (o * do).sum() + (jnp.sin(lse)).sum()  # nonzero dlse
        return f

    flash = loss(
        lambda q, k, v: flash_attention(
            q, k, v, causal=causal, interpret=True, return_lse=True
        )
    )
    ref = loss(
        lambda q, k, v: mha_reference(q, k, v, causal=causal, return_lse=True)
    )
    with jax.default_matmul_precision("highest"):
        of, lf = flash_attention(
            q, k, v, causal=causal, interpret=True, return_lse=True
        )
        orr, lr = mha_reference(q, k, v, causal=causal, return_lse=True)
        gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    assert lf.shape == (2, 3, 200) and lf.dtype == jnp.float32
    assert float(jnp.max(jnp.abs(lf - lr))) < 1e-5
    assert float(jnp.max(jnp.abs(of - orr))) < 2e-5
    for a, b, name in zip(gf, gr, "qkv"):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-3, f"d{name}"


def test_flash_jit_and_grad_compile():
    """The custom_vjp plumbing stays jittable (static meta args hash)."""
    q, k, v, do = _rand_qkv(9, 128, 128, 64)

    @jax.jit
    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, interpret=True)
        return (o * do).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    assert all(x.shape == y.shape for x, y in zip(g, (q, k, v)))
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in g)


# ------------------------------------------------------- grouped MoE FFN


def _grouped_ffn_reference(xs, w1, b1, w2, b2, starts, cap):
    """Per-group dense reference for ops/moe_gmm.py: rows
    [starts[e], starts[e] + min(count_e, cap)) go through expert e's MLP
    with the kernel's exact cast discipline; everything else is zero."""
    n, d = xs.shape
    ys = jnp.zeros_like(xs)
    for e in range(w1.shape[0]):
        s, nxt = int(starts[e]), int(starts[e + 1])
        end = s + min(nxt - s, cap)
        if end <= s:
            continue
        h = jnp.dot(xs[s:end], w1[e], preferred_element_type=jnp.float32)
        h = jax.nn.gelu(h.astype(xs.dtype) + b1[e])
        o = jnp.dot(h, w2[e], preferred_element_type=jnp.float32)
        ys = ys.at[s:end].set(o.astype(xs.dtype) + b2[e])
    return ys


def test_grouped_ffn_matches_reference():
    """Ragged groups with an empty group at each end, a group spanning a
    tile boundary, and one past capacity: outputs and all gradients match
    the per-group dense reference (fp32, interpret mode)."""
    from distributed_training_comparison_tpu.ops.moe_gmm import grouped_ffn

    ne, d, hidden, n, cap = 4, 16, 64, 100, 40
    k = jax.random.key
    xs = jax.random.normal(k(0), (n, d))
    w1 = jax.random.normal(k(1), (ne, d, hidden)) * 0.1
    b1 = jax.random.normal(k(2), (ne, hidden)) * 0.1
    w2 = jax.random.normal(k(3), (ne, hidden, d)) * 0.1
    b2 = jax.random.normal(k(4), (ne, d)) * 0.1
    # group 0 empty; group 1 spans the 64-row tile boundary; group 2
    # overflows cap=40 by 10 rows; group 3 empty (starts[3] == n)
    starts = jnp.asarray([0, 0, 50, 100, 100], jnp.int32)

    run = lambda f: f(xs, w1, b1, w2, b2, starts, cap)
    ref = run(_grouped_ffn_reference)
    got = run(
        lambda *a: grouped_ffn(*a[:5], a[5], a[6], block_rows=64, interpret=True)
    )
    assert float(jnp.max(jnp.abs(ref - got))) < 1e-6
    # dropped rows (past capacity) and empty groups produce exactly zero
    assert float(jnp.abs(got[90:]).max()) == 0.0

    def loss(f, *diff):
        return jnp.sum(f(*diff, starts, cap) ** 2)

    g_ref = jax.grad(
        lambda *a: loss(_grouped_ffn_reference, *a), argnums=(0, 1, 2, 3, 4)
    )(xs, w1, b1, w2, b2)
    g_got = jax.grad(
        lambda *a: loss(
            lambda *b: grouped_ffn(*b[:5], b[5], b[6], block_rows=64, interpret=True),
            *a,
        ),
        argnums=(0, 1, 2, 3, 4),
    )(xs, w1, b1, w2, b2)
    for a, b, name in zip(g_ref, g_got, ("xs", "w1", "b1", "w2", "b2")):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5, f"d{name}"


def test_grouped_ffn_jit_single_tile():
    """n smaller than one tile (the padding path) under jit."""
    from distributed_training_comparison_tpu.ops.moe_gmm import grouped_ffn

    ne, d, hidden, n = 2, 8, 32, 20
    k = jax.random.key
    xs = jax.random.normal(k(0), (n, d))
    w1 = jax.random.normal(k(1), (ne, d, hidden)) * 0.1
    b1 = jnp.zeros((ne, hidden))
    w2 = jax.random.normal(k(2), (ne, hidden, d)) * 0.1
    b2 = jnp.zeros((ne, d))
    starts = jnp.asarray([0, 12, 20], jnp.int32)

    @jax.jit
    def f(xs):
        return grouped_ffn(xs, w1, b1, w2, b2, starts, 16, interpret=True)

    ys = f(xs)
    ref = _grouped_ffn_reference(xs, w1, b1, w2, b2, starts, 16)
    assert float(jnp.max(jnp.abs(ys - ref))) < 1e-6
