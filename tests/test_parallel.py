"""Mesh/sharding tests on the virtual 8-device CPU mesh.

The SPMD analogue of testing DDP without GPUs (SURVEY.md §4): every
distributed code path runs in CI against
``--xla_force_host_platform_device_count=8``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_comparison_tpu.parallel import (
    batch_sharding,
    host_local_batch_slice,
    make_mesh,
    mesh_shape_for_backend,
    replicated_sharding,
    shard_batch,
)


def test_mesh_shapes_per_backend():
    assert mesh_shape_for_backend("single", 8) == (1, 1)
    assert mesh_shape_for_backend("dp", 8) == (8, 1)
    assert mesh_shape_for_backend("tpu", 8, model_parallel=2) == (4, 2)
    with pytest.raises(ValueError):
        mesh_shape_for_backend("tpu", 8, model_parallel=3)


def test_make_mesh_all_devices():
    mesh = make_mesh(backend="dp")
    assert mesh.shape == {"data": 8, "model": 1}
    assert make_mesh(backend="single").shape == {"data": 1, "model": 1}
    assert make_mesh(num_devices=4, backend="ddp").shape == {"data": 4, "model": 1}


def test_shard_batch_splits_leading_axis():
    mesh = make_mesh(backend="dp")
    batch = {"x": np.arange(64, dtype=np.float32).reshape(16, 4), "y": np.arange(16)}
    global_batch = shard_batch(batch, mesh)
    assert global_batch["x"].shape == (16, 4)
    # each device holds 1/8 of the batch rows
    shard_shapes = {s.data.shape for s in global_batch["x"].addressable_shards}
    assert shard_shapes == {(2, 4)}
    np.testing.assert_array_equal(np.asarray(global_batch["x"]), batch["x"])


def test_replicated_sharding_copies_everywhere():
    mesh = make_mesh(backend="dp")
    p = jax.device_put(jnp.ones((3, 3)), replicated_sharding(mesh))
    assert len(p.addressable_shards) == 8
    assert {s.data.shape for s in p.addressable_shards} == {(3, 3)}


def test_sharded_mean_is_global_mean():
    """A mean over a batch-sharded axis == cross-device all-reduce: the
    one-line replacement for DDP's NCCL gradient all-reduce."""
    mesh = make_mesh(backend="dp")
    x = np.arange(32, dtype=np.float32)
    gx = jax.device_put(x, batch_sharding(mesh))
    out = jax.jit(jnp.mean, out_shardings=replicated_sharding(mesh))(gx)
    assert float(out) == pytest.approx(x.mean())


def test_host_local_batch_slice_single_host():
    assert host_local_batch_slice(256) == 256  # one process in CI
