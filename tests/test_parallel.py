"""Mesh/sharding tests on the virtual 8-device CPU mesh.

The SPMD analogue of testing DDP without GPUs (SURVEY.md §4): every
distributed code path runs in CI against
``--xla_force_host_platform_device_count=8``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_comparison_tpu.parallel import (
    batch_sharding,
    host_local_batch_slice,
    make_mesh,
    mesh_shape_for_backend,
    replicated_sharding,
    shard_batch,
)


def test_mesh_shapes_per_backend():
    assert mesh_shape_for_backend("single", 8) == (1, 1, 1)
    assert mesh_shape_for_backend("dp", 8) == (8, 1, 1)
    assert mesh_shape_for_backend("tpu", 8, model_parallel=2) == (4, 2, 1)
    # the dedicated pipe axis composes with the model axis: DP×TP×PP
    assert (
        mesh_shape_for_backend("tpu", 8, model_parallel=2, pipeline_parallel=2)
        == (2, 2, 2)
    )
    assert mesh_shape_for_backend("tpu", 8, pipeline_parallel=4) == (2, 1, 4)
    with pytest.raises(ValueError):
        mesh_shape_for_backend("tpu", 8, model_parallel=3)
    with pytest.raises(ValueError):
        mesh_shape_for_backend("tpu", 8, model_parallel=2, pipeline_parallel=3)


def test_make_mesh_all_devices():
    mesh = make_mesh(backend="dp")
    assert mesh.shape == {"data": 8, "model": 1, "pipe": 1}
    assert make_mesh(backend="single").shape == {"data": 1, "model": 1, "pipe": 1}
    assert make_mesh(num_devices=4, backend="ddp").shape == {
        "data": 4, "model": 1, "pipe": 1,
    }
    assert make_mesh(8, 2, 2, backend="tpu").shape == {
        "data": 2, "model": 2, "pipe": 2,
    }


def test_shard_batch_splits_leading_axis():
    mesh = make_mesh(backend="dp")
    batch = {"x": np.arange(64, dtype=np.float32).reshape(16, 4), "y": np.arange(16)}
    global_batch = shard_batch(batch, mesh)
    assert global_batch["x"].shape == (16, 4)
    # each device holds 1/8 of the batch rows
    shard_shapes = {s.data.shape for s in global_batch["x"].addressable_shards}
    assert shard_shapes == {(2, 4)}
    np.testing.assert_array_equal(np.asarray(global_batch["x"]), batch["x"])


def test_replicated_sharding_copies_everywhere():
    mesh = make_mesh(backend="dp")
    p = jax.device_put(jnp.ones((3, 3)), replicated_sharding(mesh))
    assert len(p.addressable_shards) == 8
    assert {s.data.shape for s in p.addressable_shards} == {(3, 3)}


def test_sharded_mean_is_global_mean():
    """A mean over a batch-sharded axis == cross-device all-reduce: the
    one-line replacement for DDP's NCCL gradient all-reduce."""
    mesh = make_mesh(backend="dp")
    x = np.arange(32, dtype=np.float32)
    gx = jax.device_put(x, batch_sharding(mesh))
    out = jax.jit(jnp.mean, out_shardings=replicated_sharding(mesh))(gx)
    assert float(out) == pytest.approx(x.mean())


def test_host_local_batch_slice_single_host():
    assert host_local_batch_slice(256) == 256  # one process in CI


@pytest.mark.slow
def test_remat_tp_grad_accum_compose():
    """remat (nn.remat-wrapped blocks), tensor parallelism (name-keyed
    partition specs) and gradient accumulation must work together: remat
    preserves flax module naming, so TP specs still land, and the composed
    step compiles and runs on a (4,2) mesh."""
    import numpy as np
    from distributed_training_comparison_tpu import parallel
    from distributed_training_comparison_tpu.models.resnet import BasicBlock, ResNet
    from distributed_training_comparison_tpu.train import (
        configure_optimizers,
        create_train_state,
        make_train_step,
    )

    class HP:
        lr, weight_decay = 0.1, 1e-4
        lr_decay_step_size, lr_decay_gamma = 25, 0.1

    model = ResNet(block=BasicBlock, num_blocks=(0, 0, 1, 1), num_classes=10, remat=True)
    tx, _ = configure_optimizers(HP, steps_per_epoch=4)
    state = create_train_state(model, jax.random.key(0), tx)
    mesh = parallel.make_mesh(8, 2, backend="tpu")
    sharding = parallel.state_shardings(mesh, state)
    state = parallel.place_tree(state, sharding)
    k = state.params["stage3_block0"]["Conv_0"]["kernel"]
    assert not k.sharding.is_fully_replicated  # TP survived remat naming
    step = make_train_step(mesh, precision="bf16", state_sharding=sharding, grad_accum=2)
    bx, by = parallel.shard_batch(
        (np.zeros((16, 32, 32, 3), np.uint8), np.zeros((16,), np.int32)), mesh
    )
    st2, metrics = step(state, bx, by, jax.random.key(1))
    assert np.isfinite(float(metrics["loss"]))
    assert int(jax.device_get(st2.step)) == 1
