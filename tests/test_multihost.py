"""Multi-host (2-process) integration test on CPU.

Launches two real ``jax.distributed`` processes (coordinator on localhost,
4 virtual CPU devices each → an 8-device global mesh) running
``tests/mh_worker.py``.  This executes every ``process_count() > 1`` branch
— rendezvous, global array assembly, cross-process gradient all-reduce,
process-0 broadcast — none of which single-process CI can reach.  The
reference's multi-node path shipped with zero tests (SURVEY.md §4).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow  # multi-process / heavy-compile: full-suite only

WORKER = Path(__file__).parent / "mh_worker.py"
REPO = Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env() -> dict[str, str]:
    env = dict(os.environ)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=4"]
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # keep the TPU plugin out of the workers
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    return env


def test_two_process_distributed_train_step():
    port = _free_port()
    env = _worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(rank), str(port)],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"

    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT")][0]
        kv = dict(item.split("=") for item in line.split()[1:])
        results[int(kv["rank"])] = kv

    assert set(results) == {0, 1}
    for kv in results.values():
        assert kv["procs"] == "2"
        assert kv["step"] == "1"
    # the all-reduced loss must be bit-identical across processes — the
    # proof the two 'hosts' ran one synchronized SPMD program
    assert results[0]["loss"] == results[1]["loss"]
    assert results[0]["l2"] == results[1]["l2"]


def test_two_process_pipeline_parallel_trainer(tmp_path):
    """Pipeline parallelism with the two stages on different processes:
    every GPipe activation handoff is a cross-process ppermute, and the
    stage-sharded stacked params exercise the symmetric checkpoint fetch."""
    port = _free_port()
    env = _worker_env()
    worker = Path(__file__).parent / "mh_pp_worker.py"
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(rank), str(port), str(tmp_path)],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"

    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT")][0]
        kv = dict(item.split("=") for item in line.split()[1:])
        results[int(kv["rank"])] = kv
    assert set(results) == {0, 1}
    assert results[0]["loss"] == results[1]["loss"]
    vdir = tmp_path / f"version-{results[0]['version']}"
    assert (vdir / "last.ckpt").exists()


def test_two_process_trainer_fit_ckpt_test(tmp_path):
    """Full Trainer path over 2 processes with cross-process tensor
    parallelism: fit (symmetric TP state fetch + process-0 checkpoint
    writer) → test (found-flag broadcast).  Would deadlock if any
    collective ran asymmetrically."""
    port = _free_port()
    env = _worker_env()
    worker = Path(__file__).parent / "mh_trainer_worker.py"
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(rank), str(port), str(tmp_path)],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"

    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT")][0]
        kv = dict(item.split("=") for item in line.split()[1:])
        results[int(kv["rank"])] = kv

    assert set(results) == {0, 1}
    # global eval metrics are replicated: both 'hosts' must agree exactly
    assert results[0]["loss"] == results[1]["loss"]
    assert results[0]["top1"] == results[1]["top1"]
    # artifacts written by process 0 only
    vdir = tmp_path / f"version-{results[0]['version']}"
    assert (vdir / "last.ckpt").exists()
    assert list(vdir.glob("best_model_*.ckpt"))
