"""Unit tests for utils: seeding, meters, metrics.

Covers the semantics of the reference's ``src/single/utils.py`` symbols
(fix_seed / AverageMeter / accuracy) under the JAX rebuild.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_comparison_tpu.utils import (
    AverageMeter,
    accuracy,
    fix_seed,
    topk_correct,
)


class TestFixSeed:
    def test_returns_prng_key(self):
        key = fix_seed(42)
        # keys are typed scalars in new-style jax.random
        assert jax.random.bits(key, (2,)).shape == (2,)

    def test_deterministic(self):
        k1, k2 = fix_seed(42), fix_seed(42)
        assert jnp.array_equal(jax.random.bits(k1, (4,)), jax.random.bits(k2, (4,)))

    def test_seeds_numpy(self):
        fix_seed(7)
        a = np.random.rand(3)
        fix_seed(7)
        b = np.random.rand(3)
        assert np.array_equal(a, b)

    def test_different_seeds_differ(self):
        a = jax.random.bits(fix_seed(1), (8,))
        b = jax.random.bits(fix_seed(2), (8,))
        assert not jnp.array_equal(a, b)


class TestAverageMeter:
    def test_weighted_average(self):
        m = AverageMeter()
        m.update(1.0, n=2)
        m.update(4.0, n=1)
        assert m.val == 4.0
        assert m.sum == 6.0
        assert m.count == 3
        assert abs(m.avg - 2.0) < 1e-9

    def test_reset(self):
        m = AverageMeter()
        m.update(5.0)
        m.reset()
        assert m.val == 0.0 and m.sum == 0.0 and m.count == 0 and m.avg == 0.0


class TestAccuracy:
    def test_top1_perfect(self):
        logits = jnp.eye(4) * 10.0
        labels = jnp.arange(4)
        (top1,) = accuracy(logits, labels, topk=(1,))
        assert float(top1) == 100.0

    def test_top1_top5_known(self):
        # one sample: true class is rank 3 in the logits -> top1 miss, top5 hit
        logits = jnp.array([[5.0, 4.0, 3.0, 2.0, 1.0, 0.0]])
        labels = jnp.array([2])
        top1, top5 = accuracy(logits, labels, topk=(1, 5))
        assert float(top1) == 0.0
        assert float(top5) == 100.0

    def test_matches_numpy_reference(self):
        rng = np.random.default_rng(0)
        logits = rng.normal(size=(64, 100)).astype(np.float32)
        labels = rng.integers(0, 100, size=(64,))
        for k in (1, 5):
            order = np.argsort(-logits, axis=1)[:, :k]
            expected = float(np.mean([l in o for l, o in zip(labels, order)]) * 100)
            (got,) = accuracy(jnp.asarray(logits), jnp.asarray(labels), topk=(k,))
            assert abs(float(got) - expected) < 1e-4

    def test_topk_correct_is_jittable(self):
        f = jax.jit(lambda lg, lb: topk_correct(lg, lb, 5))
        logits = jnp.ones((8, 10))
        labels = jnp.zeros((8,), dtype=jnp.int32)
        assert f(logits, labels).shape == ()


def test_collective_census_parser():
    """The HLO census must count collectives once (-start/-done pairs are
    one op) and size payloads from result shapes, tuples included."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent))
    from tools.collective_census import census_from_hlo

    hlo = """
  %all-reduce.1 = f32[12,192]{1,0} all-reduce(f32[12,192]{1,0} %p), replica_groups={{0,1,2,3},{4,5,6,7}}
  %ag = bf16[4,64,128]{2,1,0} all-gather(bf16[4,32,128]{2,1,0} %x), replica_groups=[4,2]<=[2,4]T(1,0), dimensions={1}
  %cp-start = (bf16[2,8]{1,0}, bf16[2,8]{1,0}) collective-permute-start(bf16[2,8]{1,0} %y), source_target_pairs={{0,1},{3,4}}
  %cp-done = bf16[2,8]{1,0} collective-permute-done(%cp-start)
  %add.5 = f32[2]{0} add(f32[2]{0} %a, f32[2]{0} %b)
"""
    c = census_from_hlo(hlo)  # host_size=4: devices 0-3 host A, 4-7 host B
    # explicit groups confined to one host each → no DCN share
    assert c["all-reduce"] == (1, 12 * 192 * 4, 0)
    # transposed-iota groups {0,4},{1,5},... all span hosts → full payload
    ag_bytes = 4 * 64 * 128 * 2
    assert c["all-gather"] == (1, ag_bytes, ag_bytes)
    # -start counted once; tuple result = 2 * (2*8) bf16; one of the two
    # point-to-point pairs (3→4) crosses hosts → half the payload
    cp_bytes = 2 * 2 * 8 * 2
    assert c["collective-permute"] == (1, cp_bytes, cp_bytes // 2)
    assert "add" not in c and len(c) == 3
